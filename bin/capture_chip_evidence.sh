#!/usr/bin/env bash
# Capture the round's on-chip evidence in one shot: headline bench,
# MFU/data-plane microbenches, and the single-chip compile check.
# Artifacts land in benchmarks/ as committed JSON (suffix = $1, e.g. r02).
# Safe on a wedged transport: every stage probes with a bounded deadline
# and records an error line instead of hanging.
set -uo pipefail
cd "$(dirname "$0")/.."
SUF="${1:-local}"

run_stage() {  # run_stage <artifact> <cmd...>: a crash still records JSON
  local out="$1"; shift
  if "$@" > "$out.tmp"; then
    mv "$out.tmp" "$out"
  else
    local rc=$?  # before anything (even a $(substitution)) clobbers it
    rm -f "$out.tmp"
    if [ -s "$out" ] && ! grep -q '"error"' "$out"; then
      # never clobber a prior CLEAN capture with a crash stub — record
      # the failure beside it instead
      echo "{\"metric\": \"$(basename "$out" .json)\", \"value\": null," \
           "\"error\": \"stage crashed (rc=$rc): $*\"}" > "${out%.json}.failed.json"
    else
      echo "{\"metric\": \"$(basename "$out" .json)\", \"value\": null," \
           "\"error\": \"stage crashed (rc=$rc): $*\"}" > "$out"
    fi
  fi
  cat "$out"
}

echo "== headline bench (bench.py)"
run_stage "benchmarks/BENCH_${SUF}.json" python bench.py

echo "== microbenches incl. MFU (benchmarks/micro.py)"
run_stage "benchmarks/MICRO_${SUF}.json" python benchmarks/micro.py all

echo "== flagship LM train step (benchmarks/lm.py)"
run_stage "benchmarks/LM_${SUF}.json" python benchmarks/lm.py train

echo "== headline overhead profile (benchmarks/profile_headline.py)"
run_stage "benchmarks/PROFILE_${SUF}.json" python benchmarks/profile_headline.py primitives

echo "== single-chip compile check (__graft_entry__.entry)"
python - <<'EOF'
import json, time
from harmony_tpu.utils.devices import discover_devices
try:
    devs = discover_devices()
except RuntimeError as e:
    print(json.dumps({"metric": "entry compile", "value": None,
                      "error": str(e)}))
    raise SystemExit(0)
import jax
import __graft_entry__ as g
from harmony_tpu.utils.platform import hard_sync
fn, args = g.entry()
jfn = jax.jit(fn)  # ONE wrapper: a second jax.jit(fn) would recompile
t0 = time.perf_counter()
hard_sync(jfn(*args))  # block_until_ready lies on the lazy axon backend
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
hard_sync(jfn(*args))
print(json.dumps({"metric": "entry forward", "device": str(devs[0]),
                  "compile_sec": round(compile_s, 1),
                  "step_ms": round((time.perf_counter() - t0) * 1e3, 2)}))
EOF
