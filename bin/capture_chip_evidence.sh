#!/usr/bin/env bash
# Capture the round's on-chip evidence in one shot: headline bench,
# MFU/data-plane microbenches, and the single-chip compile check.
# Artifacts land in benchmarks/ as committed JSON (suffix = $1, e.g. r02).
# Safe on a wedged transport: every stage probes with a bounded deadline
# and records an error line instead of hanging.
set -uo pipefail
cd "$(dirname "$0")/.."
SUF="${1:-local}"

STAGE_TIMEOUT="${STAGE_TIMEOUT:-1800}"  # a wedged stage must not hang the bundle

run_stage() {  # run_stage <artifact> <cmd...>: a crash still records JSON
  local out="$1"; shift
  local rc=0
  timeout "$STAGE_TIMEOUT" "$@" > "$out.tmp" || rc=$?
  if [ "$rc" -ne 0 ]; then
    rm -f "$out.tmp"
    echo "{\"metric\": \"$(basename "$out" .json)\", \"value\": null," \
         "\"error\": \"stage crashed (rc=$rc): $*\"}" > "$out.tmp"
  fi
  # Never clobber a prior CLEAN capture with an error result — stages
  # that hit a wedged transport exit 0 with an {"error": ...} line (the
  # graceful path), so the check is on CONTENT, not exit code. Failures
  # land beside the clean artifact instead.
  if grep -q '"error"' "$out.tmp" && [ -s "$out" ] \
      && ! grep -q '"error"' "$out"; then
    mv "$out.tmp" "${out%.json}.failed.json"
  else
    mv "$out.tmp" "$out"
    rm -f "${out%.json}.failed.json"  # success supersedes old failures
  fi
  cat "$out"
}

echo "== headline bench (bench.py)"
if [ -n "${SKIP_HEADLINE:-}" ]; then
  echo "(skipped: SKIP_HEADLINE set — caller already captured it)"
else
  run_stage "benchmarks/BENCH_${SUF}.json" python bench.py
fi

echo "== microbenches incl. MFU (benchmarks/micro.py)"
run_stage "benchmarks/MICRO_${SUF}.json" python benchmarks/micro.py all

echo "== flagship LM train step (benchmarks/lm.py)"
run_stage "benchmarks/LM_${SUF}.json" python benchmarks/lm.py train

echo "== 100M-class LM train step (benchmarks/lm.py train100m)"
run_stage "benchmarks/LM100M_${SUF}.json" python benchmarks/lm.py train100m

echo "== headline overhead profile (benchmarks/profile_headline.py)"
run_stage "benchmarks/PROFILE_${SUF}.json" python benchmarks/profile_headline.py primitives

echo "== per-app throughput (benchmarks/apps.py — straggler diagnosis)"
run_stage "benchmarks/APPS_${SUF}.json" python benchmarks/apps.py all

echo "== ON-CHIP multi-tenant fairness (benchmarks/fairness.py — the"
echo "   round-3 verdict's unmeasured arm: share_all WFQ on async dispatch)"
# distinct name: FAIRNESS_<round>.json is the committed N-run CPU series
# (fairness_series.py) — a single chip run must never clobber it
run_stage "benchmarks/FAIRNESS_CHIP_${SUF}.json" python benchmarks/fairness.py

echo "== single-chip compile check (__graft_entry__.entry)"
entry_rc=0
timeout "$STAGE_TIMEOUT" python - <<'EOF' || entry_rc=$?
import json, time
from harmony_tpu.utils.devices import discover_devices
try:
    devs = discover_devices()
except RuntimeError as e:
    print(json.dumps({"metric": "entry compile", "value": None,
                      "error": str(e)}))
    raise SystemExit(0)
import jax
import __graft_entry__ as g
from harmony_tpu.utils.platform import hard_sync
fn, args = g.entry()
jfn = jax.jit(fn)  # ONE wrapper: a second jax.jit(fn) would recompile
t0 = time.perf_counter()
hard_sync(jfn(*args))  # block_until_ready lies on the lazy axon backend
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
hard_sync(jfn(*args))
print(json.dumps({"metric": "entry forward", "device": str(devs[0]),
                  "compile_sec": round(compile_s, 1),
                  "step_ms": round((time.perf_counter() - t0) * 1e3, 2)}))
EOF
if [ "$entry_rc" -ne 0 ]; then
  # same contract as run_stage: a killed/crashed stage still records JSON
  echo "{\"metric\": \"entry forward\", \"value\": null," \
       "\"error\": \"stage crashed or timed out (rc=$entry_rc)\"}"
fi
