#!/usr/bin/env bash
# Machine-checked perf history: diff the newest two committed
# BENCH_r*.json on the headline series and exit 1 on a >15% regression
# (bench.py --compare; tier-1 runs the same check as a smoke). Pass-
# through args: --dir D, --series a,b, --threshold T, or two explicit
# round files (OLD NEW).
cd "$(dirname "$0")/.." || exit 2
exec python bench.py --compare "$@"
