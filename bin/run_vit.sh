#!/usr/bin/env bash
exec python -m harmony_tpu.cli run vit "$@"
