#!/usr/bin/env bash
# Per-host bootstrap for a multi-host (TPU pod slice) deployment.
# The analogue of the reference's deploy/ + jobserver/bin/start_jobserver.sh
# pair (Hadoop/YARN confs + driver launcher): run this ONCE ON EACH HOST of
# the slice and the pod assembles itself — process 0 becomes the JobServer
# (submit to ITS host, port 43110), the rest become followers.
#
# Required environment (or flags; see `harmony-tpu start-pod --help`):
#   JAX_COORDINATOR_ADDRESS  host0-internal-ip:8476   (same on every host)
#   JAX_NUM_PROCESSES        number of hosts in the slice
#   JAX_PROCESS_ID           this host's index, 0..N-1
#
# On Cloud TPU VMs the three values come from the metadata server; with
# `gcloud compute tpus tpu-vm ssh ... --worker=all` the per-worker index is
# available as $TPU_WORKER_ID and the coordinator is worker 0's internal IP:
#
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all --command='
#     cd ~/harmony_tpu &&
#     JAX_COORDINATOR_ADDRESS=$COORD:8476 \
#     JAX_NUM_PROCESSES=$NUM_HOSTS \
#     JAX_PROCESS_ID=$TPU_WORKER_ID \
#     bin/launch_pod.sh'
#
# Keep it alive across SSH drops with tmux (or the systemd unit below):
#   tmux new-session -d -s harmony 'bin/launch_pod.sh'
#
#   # /etc/systemd/system/harmony-pod.service
#   [Service]
#   Environment=JAX_COORDINATOR_ADDRESS=10.0.0.2:8476
#   Environment=JAX_NUM_PROCESSES=4
#   Environment=JAX_PROCESS_ID=%H-derived-index
#   WorkingDirectory=/opt/harmony_tpu
#   ExecStart=/opt/harmony_tpu/bin/launch_pod.sh
#   Restart=on-failure
#
# Submitting: from anywhere that can reach host 0 —
#   bin/harmony-tpu submit mlr --port 43110      # on host 0 itself, or
#   ssh host0 'cd harmony_tpu && bin/harmony-tpu submit mlr'
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m harmony_tpu.cli start-pod "$@"
