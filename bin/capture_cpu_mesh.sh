#!/usr/bin/env bash
# CPU virtual-mesh evidence bundle — ONLY the signals that transfer from a
# 1-core host: table/data-plane bandwidths, migration stall, checkpoint IO,
# multi-worker aggregate, pod throughput, and pointers to the fairness /
# pod-tenant artifacts. Kernel sections (flash/mxu_dot/mxu push/ringflash)
# are DELIBERATELY EXCLUDED: on a 1-core CPU host they measure interpreter
# noise, not the kernel (round-3 verdict: "noise rows ... could mislead a
# reader skimming the bundle"); kernels are judged on chip captures only.
#
# Usage: bin/capture_cpu_mesh.sh [suffix]   (default r05)
set -uo pipefail
cd "$(dirname "$0")/.."
SUF="${1:-r05}"
OUT="benchmarks/CPU_MESH_${SUF}.jsonl"
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export JAX_PLATFORMS=cpu

{
  echo "# CPU virtual-mesh evidence bundle — ${SUF}. Transferable signals"
  echo "# only; kernel rows are excluded by design (1-core CPU timings of"
  echo "# MXU/flash kernels are noise — see chip captures for kernels)."
  run_row() {  # a crashed/timed-out section records an ERROR row, never
    local name="$1"; shift  # silently vanishes (silent truncation reads
    local row                # as "covered everything" — round-3 verdict)
    row="$(timeout "$1" python "${@:2}" 2>/dev/null | tail -1)"
    if [ -n "$row" ]; then
      echo "$row"
    else
      echo "{\"metric\": \"${name}\", \"value\": null," \
           "\"error\": \"section crashed or timed out\"}"
    fi
  }
  for sec in table reshard multiget sparse stall chkp; do
    run_row "micro:${sec}" 900 benchmarks/micro.py "$sec"
  done
  run_row "multiworker aggregate" 900 benchmarks/multiworker.py
  run_row "pod throughput" 1800 benchmarks/pod.py
  run_row "cross-process block migration bandwidth" 900 \
    benchmarks/blockmove_bench.py
  echo "# companion artifacts: FAIRNESS_${SUF}.json (N-run fairness series)," \
       "POD_TENANTS_${SUF}.json (carve + share_all pod tenancy)," \
       "POD_SHAREALL_${SUF}.json (share_all vs serialized aggregate A/B)," \
       "PODUNITS_${SUF}.json (unit-protocol cost at DCN RTTs)"
} > "$OUT"
echo "wrote $OUT" >&2
cat "$OUT"
