#!/usr/bin/env bash
# Post-bring-up smoke validation for a harmony_tpu pod (docs/DEPLOY.md §5).
# Run from host 0 (or anywhere that reaches its submit port): submits one
# tiny MLR job with a checkpoint snapshot, polls to completion, verifies
# the server answers and the job drained. Exit 0 = the pod trains.
#
# Usage: bin/pod_smoke.sh [--port 43110] [--chkp]
#   --chkp  also exercise the model-checkpoint path (needs the pod
#           started with a --chkp-root / HARMONY_POD_CHKP_ROOT)
set -euo pipefail
cd "$(dirname "$0")/.."

PORT=43110
CHKP=""
while [[ $# -gt 0 ]]; do
  case "$1" in
    --port) PORT="$2"; shift 2 ;;
    --chkp) CHKP="--model-chkp-period 1"; shift ;;
    *) echo "unknown arg $1" >&2; exit 2 ;;
  esac
done

echo "== pod status" >&2
python -m harmony_tpu.cli status --port "$PORT"

echo "== submitting smoke job" >&2
# shellcheck disable=SC2086
python -m harmony_tpu.cli submit mlr --port "$PORT" \
  --job-id "smoke-$$" --epochs 2 --batches 2 $CHKP

echo "== waiting for drain" >&2
for _ in $(seq 1 600); do
  if ! python -m harmony_tpu.cli status --port "$PORT" \
      | grep -q '"running": *true'; then
    echo "POD_SMOKE_OK" >&2
    exit 0
  fi
  sleep 1
done
echo "POD_SMOKE_TIMEOUT: job never drained" >&2
exit 1
