#!/usr/bin/env bash
# Seeded chaos tiers (docs/FAULT_TOLERANCE.md §Chaos orchestrator).
#
#   bin/chaos.sh             fast tier: the tier-1 chaos marker tests
#                            (schedule determinism, fault-class
#                            semantics, fast end-to-end scenarios) plus
#                            a --quick sweep (no HA takeover cells)
#   bin/chaos.sh --runslow   full tier: slow-marked HA scenarios and
#                            the complete sweep grid, the capture that
#                            becomes benchmarks/CHAOS_r*.json
#
# Any red cell prints its (seed, scenario, intensity) row — replay it
# byte-identically with:
#   python -c 'from harmony_tpu.faults import chaos; \
#              print(chaos.run_scenario(SEED, intensity=I, scenario="NAME"))'
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

SLOW=""
SWEEP_ARGS="--quick"
if [[ "${1:-}" == "--runslow" ]]; then
  SLOW="--runslow"
  SWEEP_ARGS=""
fi

echo "# chaos tests (${SLOW:-fast tier})" >&2
python -m pytest tests/test_chaos.py -q -m chaos ${SLOW} \
  -p no:cacheprovider -p no:randomly

echo "# chaos sweep ${SWEEP_ARGS:-(full grid)}" >&2
python benchmarks/chaos_sweep.py ${SWEEP_ARGS} > /tmp/chaos_sweep.json
python - <<'EOF'
import json
doc = json.load(open("/tmp/chaos_sweep.json"))
s = doc["summary"]
print(f"chaos sweep: {s['scenarios_ok']}/{s['scenarios_run']} scenarios "
      f"green, violations={s['invariant_violations']}")
for cell in doc["grid"]:
    if not cell["ok"]:
        print("  RED:", {k: cell[k] for k in
                         ("seed", "scenario", "intensity", "violations")})
raise SystemExit(0 if s["scenarios_ok"] == s["scenarios_run"] else 1)
EOF
