#!/usr/bin/env bash
# harmonylint over the tree (docs/STATIC_ANALYSIS.md). Exit 1 on any
# unallowlisted finding — same contract tier-1 enforces. Pass-through
# args: --json, --passes a,b, --verbose, --write-baseline PATH ...
exec python -m harmony_tpu.cli lint "$@"
