#!/usr/bin/env bash
# Wait for the remote chip transport to come back, then capture the
# round's headline evidence once and exit. The transport wedges for hours
# at a time; healthy windows are short and unpredictable, so polling is
# the only way to land a clean capture. Artifacts only overwrite on a
# clean (error-free) bench line.
#
# Usage: bin/watch_chip.sh [suffix] [poll_seconds]
set -uo pipefail
cd "$(dirname "$0")/.."
SUF="${1:-r02_chip}"
POLL="${2:-300}"
PROBE='import jax; ds = jax.devices(); print("PROBE", ds[0].platform)'

while true; do
  # the axon client reports device platform "tpu"; match "axon" too in
  # case a future plugin surfaces its registry name instead
  if timeout 60 python -c "$PROBE" 2>"benchmarks/.watch_probe.log" \
      | grep -Eq "PROBE (tpu|axon)"; then
    echo "$(date -Is) chip healthy — capturing" >&2
    if timeout 1800 python bench.py > "benchmarks/.BENCH_watch.json" \
        2> "benchmarks/.watch_bench.log" \
        && ! grep -q '"error"' "benchmarks/.BENCH_watch.json"; then
      mv "benchmarks/.BENCH_watch.json" "benchmarks/BENCH_${SUF}.json"
      echo "$(date -Is) clean headline captured:" >&2
      cat "benchmarks/BENCH_${SUF}.json" >&2
      # same window: refresh the rest of the evidence (micro MFU, LM,
      # profile, entry check) WITHOUT re-running the ~10-min headline we
      # just landed; run_stage keeps prior clean artifacts on failure
      SKIP_HEADLINE=1 bash bin/capture_chip_evidence.sh "${SUF}" >&2 || true
      exit 0
    fi
    echo "$(date -Is) capture not clean; will retry" >&2
  fi
  sleep "$POLL"
done
