"""Asynchronous host→device input pipeline for the training hot loop.

The dolphin loop dispatches steps asynchronously, but every batch used to
be *produced* synchronously on the hot path: the per-batch numpy gather in
``TrainingDataProvider.epoch_batches()`` and the blocking ``device_put``
in ``WorkerTasklet._shard_batch`` both ran inside the TaskUnit COMP scope,
so host assembly and H2D transfer serialized with device dispatch and
inflated the per-unit cost fed to the fair queue. This module disaggregates
input production from the training step — the in-process analogue of
tf.data service's case for disaggregating ML input processing (PAPERS.md):

  * a PRODUCER thread owns one epoch of ``epoch_batches()`` — the epoch
    RNG draw and per-batch assembly happen off the training thread, in the
    same order as the synchronous path, so a fixed seed yields the same
    batch sequence bit-for-bit;
  * each assembled batch is STAGED with a sharding-aware ``device_put``
    into a bounded :class:`~harmony_tpu.data.loader.StageRing` whose depth
    tracks the worker's live in-flight cap (shallow under TaskUnit
    contention so no tenant's staged backlog taxes HBM or fairness, deep
    otherwise), overlapping H2D transfer with device compute;
  * under multi-tenancy the staging transfers are typed as NET TaskUnits
    (the reference's PULL/PUSH resource class) so they ride the fair queue
    instead of colliding with peers' COMP units at the dispatch lock;
  * a :class:`LayoutAnnouncerMixin` reshard announcement invalidates the
    in-flight staged device copies — the host copies stay, and the
    consumer re-places them on the live mesh at consume time (a staged
    batch also self-invalidates if its sharding no longer matches the
    step's, so a flip the announcement missed is still safe).

Instrumented end to end: ``dolphin.prefetch.produce`` / ``.stage`` /
``.wait`` trace spans plus the ring's staged/hit/stall/idle counters, which
the worker reports per epoch as ``InputPipelineMetrics`` through the
existing metric collector (and so the dashboard connector).
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import numpy as np

from harmony_tpu.data.loader import StageRing
from harmony_tpu.runtime.taskunit import TaskUnitAborted
from harmony_tpu.tracing import trace_span


@dataclasses.dataclass
class StagedBatch:
    """One prefetched batch: the host tuple plus (optionally) its staged
    device copy and the sharding it was placed with."""

    index: int
    host: Tuple[np.ndarray, ...]
    device: Optional[Tuple[Any, ...]]
    sharding: Any

    def take(self, live_sharding: Any) -> Optional[Tuple[Any, ...]]:
        """The staged device copy iff it still matches the live batch
        sharding; None means the consumer must re-place ``host``."""
        if self.device is None or self.sharding != live_sharding:
            return None
        return self.device


class PrefetchPipeline:
    """One epoch's background input producer.

    Construction starts the producer thread immediately; iterate the
    pipeline to consume staged batches in order; ``close()`` (idempotent,
    also run when iteration ends) stops the producer and joins it.

    ``sharding_fn`` is read per batch so stages follow a live reshard;
    ``depth_fn`` is read per put so the ring tracks the worker's in-flight
    cap; ``net_scope`` (optional) is called with an abort predicate (true
    once the ring is closed) and must return a context manager — staging
    rides the TaskUnit fair queue as a NET unit whose admission wait stays
    interruptible, so teardown never hangs on a grant that cannot arrive;
    ``skip_stage_fn`` (optional) suppresses the ``device_put`` for batches
    that are already device-resident (one evicted cache entry must not
    re-transfer the whole epoch) — those flow through host-only and the
    consumer's cache lookup serves them; ``epoch_source`` (optional)
    replaces the provider's local assembly with an external batch stream
    — the input-service feed (harmony_tpu/inputsvc) — leaving staging,
    invalidation and the consumer contract untouched.
    """

    JOIN_TIMEOUT = 10.0

    def __init__(
        self,
        provider: Any,
        sharding_fn: Callable[[], Any],
        depth_fn: Callable[[], int],
        *,
        epoch: int = 0,
        job_id: str = "",
        net_scope: Optional[Callable[[Callable[[], bool]], Any]] = None,
        skip_stage_fn: Optional[Callable[[int], bool]] = None,
        epoch_source: Optional[Callable[[], Any]] = None,
    ) -> None:
        self._provider = provider
        self._sharding_fn = sharding_fn
        self._net_scope = net_scope
        self._skip_stage_fn = skip_stage_fn
        self._epoch_source = epoch_source
        self._ring = StageRing(depth_fn)
        self._epoch = epoch
        self._job_id = job_id
        self._host_only = False  # see stop_staging()
        self.produce_sec = 0.0  # host assembly (gather/stack) seconds
        self.stage_sec = 0.0    # device_put seconds (incl. NET admission)
        # staged device copies DROPPED before use, by reason ("reshard" =
        # layout-change invalidation, "demote" = host-only demotion) —
        # mutated from the announcement-listener thread while the
        # producer/consumer run, so guarded; mirrored onto the registry's
        # harmony_input_dropped_total{reason} counter
        self._drop_lock = threading.Lock()
        self.dropped: dict = {}
        self._thread = threading.Thread(
            target=self._produce,
            name=f"prefetch-{job_id or 'job'}-e{epoch}",
            daemon=True,
        )
        self._thread.start()

    # -- producer side ---------------------------------------------------

    def _produce(self) -> None:
        ring = self._ring
        try:
            with trace_span(
                "dolphin.prefetch.produce",
                job_id=self._job_id, epoch=self._epoch,
            ) as span:
                it = enumerate(
                    self._epoch_source()
                    if self._epoch_source is not None
                    else self._provider.epoch_batches()
                )
                while True:
                    t0 = time.perf_counter()
                    if (self._epoch_source is not None
                            and self._net_scope is not None):
                        # service fetches are network work: ride the fair
                        # queue as NET units (same class as staging) so a
                        # tenant's input pulls queue behind its own share
                        with self._net_scope(self._closed):
                            nxt = next(it, None)
                    else:
                        nxt = next(it, None)
                    self.produce_sec += time.perf_counter() - t0
                    if nxt is None:
                        break
                    idx, host = nxt
                    if self._host_only or (
                        self._skip_stage_fn is not None
                        and self._skip_stage_fn(idx)
                    ):
                        # host-only: demoted (assembly continues — it owns
                        # the epoch RNG — but transfers stop) or the batch
                        # is already device-resident (consumer's cache
                        # lookup serves it; re-transfer would be waste)
                        if not ring.put(StagedBatch(idx, host, None, None)):
                            return
                        continue
                    sharding = self._sharding_fn()
                    scope = (self._net_scope(self._closed)
                             if self._net_scope is not None
                             else contextlib.nullcontext())
                    t0 = time.perf_counter()
                    with trace_span(
                        "dolphin.prefetch.stage",
                        job_id=self._job_id, epoch=self._epoch, batch=idx,
                    ):
                        with scope:
                            device = tuple(
                                jax.device_put(a, sharding) for a in host
                            )
                    self.stage_sec += time.perf_counter() - t0
                    if not ring.put(StagedBatch(idx, host, device, sharding)):
                        return  # consumer closed the epoch early
                if span is not None:
                    span.annotate("staged", ring.staged)
                    span.annotate("produce_sec", round(self.produce_sec, 6))
                    span.annotate("stage_sec", round(self.stage_sec, 6))
                    span.annotate("idle_sec", round(ring.producer_idle_sec, 6))
        except TaskUnitAborted:
            return  # ring closed mid-admission-wait: quiet teardown
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer
            ring.set_error(e)
        else:
            ring.finish()

    def _closed(self) -> bool:
        """Abort predicate handed to the NET admission wait."""
        return self._ring.closed

    # -- consumer side ---------------------------------------------------

    def __iter__(self) -> Iterator[StagedBatch]:
        ring = self._ring
        while True:
            if ring.depth() == 0 and self._thread.is_alive():
                # about to block on the producer: that is the stall the
                # pipeline exists to eliminate — make it visible
                with trace_span(
                    "dolphin.prefetch.wait",
                    job_id=self._job_id, epoch=self._epoch,
                ):
                    item = ring.get()
            else:
                item = ring.get()
            if item is StageRing.DONE:
                return
            yield item

    def invalidate(self, reason: str = "reshard") -> int:
        """Reshard announcement hook: drop the staged device copies (host
        copies stay — the consumer re-places them on the live mesh), and
        let new stages pick up the new sharding from ``sharding_fn``.
        Returns the number of staged batches invalidated; copies that
        actually existed count into ``dropped[reason]`` and the
        ``harmony_input_dropped_total{reason}`` registry counter (they
        are H2D transfers paid and thrown away — stats() used to lose
        them entirely)."""
        box = [0]

        def drop(item: StagedBatch) -> None:
            if item.device is not None:
                box[0] += 1
            item.device = None

        n = self._ring.apply(drop)
        if box[0]:
            with self._drop_lock:
                self.dropped[reason] = self.dropped.get(reason, 0) + box[0]
            try:
                from harmony_tpu.metrics.registry import get_registry

                get_registry().counter(
                    "harmony_input_dropped_total",
                    "Staged input batches whose device copies were "
                    "dropped before use, by reason (reshard "
                    "invalidation / host-only demotion)",
                    ("reason",),
                ).labels(reason=reason).inc(box[0])
            except Exception:
                pass  # metrics are an observer, never a dependency
        return n

    def stop_staging(self) -> int:
        """Demote the pipeline to host-only production: the producer keeps
        assembling batches (it owns the epoch RNG draw, so abandoning it
        would double-advance a seeded shuffle) but stops issuing
        ``device_put``s — the consumer places every batch on the live mesh
        itself. Used when background transfers become unsafe mid-epoch
        (a reshard onto a process-spanning mesh, where a device_put is
        collective-backed and must not race the training thread's
        dispatches). Also invalidates already-staged copies; returns the
        invalidated count."""
        self._host_only = True
        return self.invalidate(reason="demote")

    def close(self) -> None:
        """Stop the producer (idempotent) and join it — no leaked thread.
        Safe from the consumer thread at any point, including after a
        producer exception already surfaced."""
        self._ring.close()
        self._thread.join(timeout=self.JOIN_TIMEOUT)

    @property
    def thread_alive(self) -> bool:
        return self._thread.is_alive()

    def stats(self) -> dict:
        r = self._ring
        with self._drop_lock:
            dropped = dict(self.dropped)
        return {
            "staged": r.staged,
            "max_depth": r.max_depth,
            "producer_idle_sec": r.producer_idle_sec,
            "consumer_stall_sec": r.consumer_stall_sec,
            "produce_sec": self.produce_sec,
            "stage_sec": self.stage_sec,
            "dropped": dropped,
            "dropped_batches": sum(dropped.values()),
        }
