"""Model checkpoint chaining + offline evaluation replay.

Parity with the reference's ModelChkpManager (dolphin/core/master/
ModelChkpManager.java:40-80: chain model-table checkpoints during training,
restore them between evaluation rounds) and ModelEvaluator /
ModelEvaluationTasklet (dolphin/core/worker/ModelEvaluator.java: offline
evaluation over checkpointed model tables + test data, run at job end or
deferred to server shutdown — DolphinMaster.evaluate()).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from harmony_tpu.checkpoint.manager import CheckpointManager
from harmony_tpu.dolphin.trainer import Trainer
from harmony_tpu.runtime.master import ETMaster, TableHandle


class ModelChkpManager:
    """Chains per-epoch snapshots of the model table during training."""

    def __init__(
        self,
        chkp_manager: CheckpointManager,
        handle: TableHandle,
        period: int = 1,
        commit: bool = True,
    ) -> None:
        self._mgr = chkp_manager
        self._handle = handle
        self._period = max(1, period)
        self._commit = commit
        self.chkp_ids: List[str] = []

    def on_epoch(self, epoch_idx: int) -> Optional[str]:
        """Epoch hook: snapshot every ``period`` epochs. Plugs into
        WorkerTasklet(epoch_callback=...)."""
        if (epoch_idx + 1) % self._period:
            return None
        cid = self._mgr.checkpoint(self._handle, commit=self._commit)
        self.chkp_ids.append(cid)
        return cid


class ModelEvaluator:
    """Replays checkpoints against a trainer's evaluate() on test data.

    The reference restores each chained checkpoint into a fresh table and
    runs ModelEvaluationTasklet over it; here each checkpoint restores into
    a temporary table on the given executors, evaluates, and drops.
    """

    def __init__(self, master: ETMaster, chkp_manager: CheckpointManager) -> None:
        self._master = master
        self._mgr = chkp_manager

    def evaluate_checkpoints(
        self,
        chkp_ids: List[str],
        trainer: Trainer,
        test_batch: Tuple[np.ndarray, ...],
        executor_ids: List[str],
    ) -> List[Dict[str, float]]:
        eval_fn = jax.jit(trainer.evaluate)
        out: List[Dict[str, float]] = []
        for i, cid in enumerate(chkp_ids):
            handle = self._mgr.restore(
                self._master, cid, executor_ids, table_id=f"__eval__:{cid}"
            )
            try:
                model = handle.table.pull_array()
                metrics = eval_fn(model, tuple(map(np.asarray, test_batch)))
                out.append({k: float(v) for k, v in metrics.items()})
            finally:
                handle.drop()
        return out
