"""Model checkpoint chaining + offline evaluation replay.

Parity with the reference's ModelChkpManager (dolphin/core/master/
ModelChkpManager.java:40-80: chain model-table checkpoints during training,
restore them between evaluation rounds) and ModelEvaluator /
ModelEvaluationTasklet (dolphin/core/worker/ModelEvaluator.java: offline
evaluation over checkpointed model tables + test data, run at job end or
deferred to server shutdown — DolphinMaster.evaluate()).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from harmony_tpu.checkpoint.manager import (
    CheckpointManager,
    CheckpointStillWriting,
    PendingCheckpoint,
)
from harmony_tpu.dolphin.trainer import Trainer
from harmony_tpu.runtime.master import ETMaster, TableHandle


class ModelChkpManager:
    """Chains per-epoch snapshots of the model table during training.

    Snapshots are ASYNC: the epoch hook runs on the worker's thread, so a
    blocking checkpoint (device->host transfer + file IO) would stall
    training for the write duration every period. The device-side snapshot
    is atomic at the hook; the bytes drain in the background and
    ``drain()`` (called before evaluation / at job end) joins the writers.
    """

    # Cap on concurrent background writers: each in-flight checkpoint pins
    # one device-side table copy, so unbounded pendings could OOM a chip
    # when the hook outpaces the disk.
    MAX_PENDING = 2

    def __init__(
        self,
        chkp_manager: CheckpointManager,
        handle: TableHandle,
        period: int = 1,
        commit: bool = True,
    ) -> None:
        self._mgr = chkp_manager
        self._handle = handle
        self._period = max(1, period)
        self._commit = commit
        self.chkp_ids: List[str] = []
        self._pending: List[PendingCheckpoint] = []

    def on_epoch(self, epoch_idx: int) -> Optional[str]:
        """Epoch hook: snapshot every ``period`` epochs. Plugs into
        WorkerTasklet(epoch_callback=...)."""
        if (epoch_idx + 1) % self._period:
            return None
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        meta = {"epoch": float(epoch_idx)}  # the resume path's restart key
        if mesh_spans_processes(self._handle.table.mesh):
            # Pod: the checkpoint is a synchronous mesh collective (every
            # process's chief worker reaches this hook at the same point in
            # its deterministic schedule; checkpoint_async's background
            # barriers would race the lockstep dispatch order).
            cid = self._mgr.checkpoint(self._handle, commit=self._commit,
                                       app_meta=meta)
            self.chkp_ids.append(cid)
            return cid
        while len(self._pending) >= self.MAX_PENDING:
            oldest = self._pending.pop(0)  # backpressure: join the oldest
            try:
                oldest.wait()
            except BaseException:
                # keep the chain consistent even on the backpressure path:
                # a failed writer's id must not survive as a replayable id
                if oldest.chkp_id in self.chkp_ids:
                    self.chkp_ids.remove(oldest.chkp_id)
                raise
        p = self._mgr.checkpoint_async(self._handle, commit=self._commit,
                                       app_meta=meta)
        self._pending.append(p)
        self.chkp_ids.append(p.chkp_id)
        return p.chkp_id

    def drain(self, timeout: float = 300.0) -> List[str]:
        """Join ALL background writers; failed ids are removed from the
        chain so the survivors stay replayable, then the first failure is
        re-raised. A TIMED-OUT writer is different from a failed one: its
        checkpoint may still complete, so its id stays in the chain and
        its handle stays pending — call drain() again to re-join it.
        Call before evaluating the chain / dropping the table."""
        errors: List[BaseException] = []
        still_pending: List[PendingCheckpoint] = []
        for p in self._pending:
            try:
                p.wait(timeout=timeout)
            except CheckpointStillWriting as e:
                still_pending.append(p)  # in flight, not dead
                errors.append(e)
            except BaseException as e:  # noqa: BLE001 - reported below
                errors.append(e)
                if p.chkp_id in self.chkp_ids:
                    self.chkp_ids.remove(p.chkp_id)
        self._pending = still_pending
        if errors:
            # A real writer failure outranks a timeout: the timeout's
            # pending survives for a retry, the failure would be lost.
            for e in errors:
                if not isinstance(e, CheckpointStillWriting):
                    raise e
            raise errors[0]
        return list(self.chkp_ids)


def resolve_eval_inputs(config):
    """(trainer, batch) for a job's offline model evaluation, resolved
    from the serializable JobConfig — THE one resolution shared by the
    leader's deferred-eval closure and the pod follower's collective leg
    (they must issue byte-identical restore/evaluate collectives; two
    hand-copied resolutions would silently desynchronize them). fn and
    args fall back TOGETHER: pairing a custom test_data_fn with the
    training data_args would call it with foreign kwargs."""
    import numpy as np

    from harmony_tpu.config.base import resolve_symbol

    user = config.user
    if "test_data_fn" in user:
        fn = resolve_symbol(user["test_data_fn"])
        args = user.get("test_data_args", {})
    else:
        fn = resolve_symbol(user["data_fn"])
        args = user.get("test_data_args", user.get("data_args", {}))
    out = fn(**args)
    batch = tuple(
        np.asarray(a)
        for a in (out if isinstance(out, (tuple, list)) else (out,))
    )
    trainer = resolve_symbol(config.trainer)(**config.params.app_params)
    return trainer, batch


class ModelEvaluator:
    """Replays checkpoints against a trainer's evaluate() on test data.

    The reference restores each chained checkpoint into a fresh table and
    runs ModelEvaluationTasklet over it; here each checkpoint restores into
    a temporary table on the given executors, evaluates, and drops.
    """

    def __init__(self, master: ETMaster, chkp_manager: CheckpointManager) -> None:
        self._master = master
        self._mgr = chkp_manager

    def evaluate_checkpoints(
        self,
        chkp_ids: List[str],
        trainer: Trainer,
        test_batch: Tuple[np.ndarray, ...],
        executor_ids: List[str],
    ) -> List[Dict[str, float]]:
        eval_fn = jax.jit(trainer.evaluate)
        out: List[Dict[str, float]] = []
        for i, cid in enumerate(chkp_ids):
            handle = self._mgr.restore(
                self._master, cid, executor_ids, table_id=f"__eval__:{cid}"
            )
            try:
                if handle.table.spec.config.sparse:
                    # no full-model array exists over an unbounded key
                    # domain: trainers provide a keyed-lookup evaluation
                    sparse_eval = getattr(trainer, "evaluate_sparse", None)
                    if sparse_eval is None:
                        raise NotImplementedError(
                            f"{type(trainer).__name__} has no "
                            "evaluate_sparse(table, batch); required to "
                            "evaluate a sparse (hash-backed) checkpoint"
                        )
                    metrics = sparse_eval(
                        handle.table, tuple(map(np.asarray, test_batch))
                    )
                else:
                    model = handle.table.pull_array()
                    metrics = eval_fn(model, tuple(map(np.asarray, test_batch)))
                out.append({k: float(v) for k, v in metrics.items()})
            finally:
                handle.drop()
        return out
