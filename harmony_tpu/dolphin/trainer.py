"""The Trainer SPI — the user-facing training contract.

Capability parity with the reference's 4-phase Trainer API
(dolphin/core/worker/Trainer.java:44-92):

  reference                      harmony_tpu
  ---------                      -----------
  initGlobalSettings()           init_global_settings(ctx)
  setMiniBatchData(data)         (framework passes the batch to compute)
  pullModel(data)                pull mode: "all" or pull_keys(batch)
  localCompute(data)             compute(model, batch) -> (delta, metrics)
  pushUpdate()                   (framework pushes compute's delta)
  onEpochFinished(epoch)         on_epoch_finished(ctx, epoch)
  evaluateModel(in, test, table) evaluate(model, batch) -> metrics
  cleanup()                      cleanup(ctx)

TPU-first difference, and why the shape is not a translation: the reference
runs pull/compute/push as three host-driven RPC phases. Here ``compute`` is a
*pure jax function* so the framework can fuse PULL (gather/all-gather), COMP
(MXU math), and PUSH (scatter / reduction) into ONE jitted, SPMD-sharded
step — XLA inserts the cross-chip collectives that replace the reference's
per-key RPCs. Phase identities survive (they are still announced to the
TaskUnit scheduler for multi-job interleaving) but the hot loop is a single
compiled program.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from harmony_tpu.config.params import TrainerParams


@dataclasses.dataclass
class TrainerContext:
    """What a trainer sees of the framework: its tables and hyper-params.

    ``model_table`` is the PS table (the reference's model table on server
    executors); ``local_table`` the optional worker-local table (ref:
    DolphinJobEntity local-model table, e.g. NMF's L-matrix rows)."""

    params: TrainerParams
    model_table: Any = None          # DenseTable
    local_table: Any = None          # DenseTable or None
    worker_id: str = "worker-0"
    num_workers: int = 1


class Trainer:
    """Base class; apps override the pure parts.

    ``pull_mode`` selects the PULL realization:
      * "all"  — the whole model is pulled each batch (MLR/Lasso/NMF-R style
        whole-table pull; realized as all-gather of the sharded table).
        ``compute`` receives ``model`` of shape [capacity, *value_shape].
      * "keys" — ``pull_keys(batch)`` names the rows needed (sparse apps);
        ``compute`` receives the gathered rows.
    """

    pull_mode: str = "all"
    # True: the job also carries a worker-local table (ref: DolphinJobEntity
    # optional local-model table, e.g. NMF's L-matrix rows); the fused step
    # then threads BOTH table arrays functionally and ``compute_with_local``
    # is used instead of ``compute``.
    uses_local_table: bool = False
    # Name of the trainer's objective in its compute() metrics when it is
    # NOT called "loss" (e.g. LDA's "log_likelihood"): per-batch/epoch
    # progress series fall back to it. None = only "loss" counts; other
    # metric keys are counters, never relabeled as a loss.
    objective_metric: "str | None" = None

    # -- lifecycle (host side) ------------------------------------------

    def init_global_settings(self, ctx: TrainerContext) -> None:
        """One-time setup before the first epoch (may push initial model
        values into the table)."""

    def on_training_start(self, ctx: TrainerContext, starting_epoch: int) -> None:
        """Called by the worker just before the epoch loop with the resume
        epoch (ref: StartingEpochIdx reaching the worker conf) — trainers
        with epoch-dependent state (LDA's PRNG fold, decay schedules) must
        seed from here, not assume epoch 0."""

    #: OPT-IN: set True on your subclass when :meth:`on_epoch_finished`
    #: depends only on ``epoch_idx`` and the trainer's OWN attributes
    #: (decay schedules, PRNG epoch counters) — never on trained values,
    #: pulled models, or tables. The worker then may invoke it between the
    #: dispatches of a multi-epoch fused window, BEFORE that epoch's
    #: device results have drained (collapsing one host<->device round
    #: trip per epoch into one per window). Trainers that don't override
    #: the hook at all are windowable regardless (the no-op reads
    #: nothing); the flag matters only for overriders.
    epoch_hook_windowable = False

    def on_epoch_finished(self, ctx: TrainerContext, epoch_idx: int) -> None:
        """Per-epoch hook (host side; may adjust step size etc. — see
        ``epoch_hook_windowable`` if it reads trained state)."""

    def cleanup(self, ctx: TrainerContext) -> None:
        """Final hook after the last epoch."""

    @classmethod
    def _epoch_hook_windowable(cls, trainer: "Trainer") -> bool:
        """Whether ``trainer``'s on_epoch_finished may run between the
        dispatches of a multi-epoch window (before results drain).

        True for the base no-op. For overriders, the ``epoch_hook_
        windowable`` opt-in must be declared AT OR BELOW the class that
        defines the effective hook — a flag inherited from above describes
        a different (ancestor) hook, and a subclass replacing the hook
        must re-opt-in for its own. Instance-level assignment wins."""
        if "epoch_hook_windowable" in trainer.__dict__:
            return bool(trainer.__dict__["epoch_hook_windowable"])
        mro = type(trainer).__mro__
        hook_owner = next(c for c in mro if "on_epoch_finished" in vars(c))
        if hook_owner is Trainer:
            return True  # un-overridden no-op reads nothing
        flag_owner = next(
            (c for c in mro if "epoch_hook_windowable" in vars(c)), None
        )
        if flag_owner is None or not vars(flag_owner)["epoch_hook_windowable"]:
            return False
        return mro.index(flag_owner) <= mro.index(hook_owner)

    # -- pure parts (traced into the fused step) ------------------------

    def hyperparams(self) -> Dict[str, float]:
        """Host-side hyper-parameters passed INTO the jitted step each epoch
        (learning rate etc.). Values reach ``compute`` as traced scalars, so
        per-epoch changes (decay in on_epoch_finished) take effect without
        recompiling — a baked-in Python float would be a trace-time constant
        and silently never decay."""
        return {}

    def jit_signature(self) -> "tuple | None":
        """Structural identity of this trainer's TRACED behavior, or None.

        Jobs whose trainers report equal signatures (together with equal
        table/mesh/batch signatures) reuse each other's compiled step
        programs across submissions (runtime/progcache) — the long-running
        JobServer's resubmit-the-same-app pattern stops paying a recompile
        per job, which on a remote-attached accelerator dominates short
        jobs.

        Contract: the signature must determine everything the trainer's
        traced functions — ``compute``/``compute_with_local``,
        ``pull_keys``, ``evaluate``, and the ``hyperparams`` key set —
        would trace (the worker caches its eval program under the same
        key). The default derives it
        from the instance ``__dict__`` when every attribute is a plain
        scalar (int/float/str/bool/None, or flat tuples thereof) and opts
        out (None) otherwise — a trainer holding arrays, callables or other
        objects cannot be structurally named, and silently sharing programs
        would be worse than recompiling. Note scalars that compute() bakes
        into the trace are frozen at first dispatch ANYWAY (mutating them
        mid-job never retraces), so keying on their at-build values adds no
        new staleness hazard; per-epoch knobs belong in hyperparams().
        """
        items = []
        for k, v in sorted(self.__dict__.items()):
            # Type-tag every scalar: Python's cross-type equality
            # (True == 1 == 1.0) would otherwise collide keys whose traced
            # programs differ (an int baked into a trace doesn't promote
            # like a float would).
            if isinstance(v, (int, float, str, bool, type(None))):
                items.append((k, type(v).__name__, v))
            elif isinstance(v, tuple) and all(
                isinstance(x, (int, float, str, bool)) for x in v
            ):
                items.append((k, tuple((type(x).__name__, x) for x in v)))
            else:
                return None
        return (type(self).__module__, type(self).__qualname__, tuple(items))

    def pull_keys(self, batch: Any) -> jnp.ndarray:
        """keys to pull for this batch (pull_mode == "keys" only)."""
        raise NotImplementedError

    def mask_delta(self, delta: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
        """Hash-backed tables only: reconcile the push delta with the
        admission mask (``ok`` per pulled key) BEFORE the push. Override
        when rows carry cross-row invariants that a dropped row must leave
        consistent (e.g. LDA's summary row = sum of word rows). Default:
        identity — the table itself already drops ok=False rows."""
        return delta

    def compute(
        self, model: jnp.ndarray, batch: Any, hyper: Dict[str, jnp.ndarray]
    ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        """The mini-batch computation. Returns ``(delta, metrics)`` where
        ``delta`` matches ``model``'s shape and is folded into the table via
        the table's update function (push). Must be jax-traceable.
        ``hyper`` carries the values from :meth:`hyperparams`."""
        raise NotImplementedError

    def compute_with_local(
        self,
        model: jnp.ndarray,
        local: jnp.ndarray,
        batch: Any,
        hyper: Dict[str, jnp.ndarray],
    ) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
        """Variant for uses_local_table trainers: returns
        ``(model_delta, new_local_array, metrics)`` — the model delta folds
        through the PS table's update fn; the local array is replaced
        wholesale (worker-private state needs no update-fn semantics)."""
        raise NotImplementedError

    def local_table_config(self):
        """Schema of the worker-local table (uses_local_table only)."""
        raise NotImplementedError

    def evaluate(
        self, model: jnp.ndarray, batch: Any
    ) -> Dict[str, jnp.ndarray]:
        """Model evaluation on held-out data (ref: evaluateModel)."""
        raise NotImplementedError
