"""Stateful training optimizers over PS-table storage.

The reference's trainers are plain SGD-family updates applied through the
table's UpdateFunction (server-side fold). Momentum/Adam need per-parameter
STATE shared exactly like the parameters — so the state lives in the same
elastic table, as extra row sections:

    rows = [ params | m (slot 1) | v (slot 2) | counter row ]

Every section reshards, checkpoints and migrates with the table for free.
The update math is pure (jit-safe) over flat vectors; trainers split their
pulled rows into sections, call :func:`apply`, and push back per-section
deltas (additive fold — delta = new - old).
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

SLOTS = {"sgd": 0, "momentum": 1, "adagrad": 1, "rmsprop": 1, "adam": 2}


def num_slots(name: str) -> int:
    try:
        return SLOTS[name]
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(SLOTS)}") from None


def apply(
    name: str,
    params: jnp.ndarray,       # [n] flat
    grads: jnp.ndarray,        # [n] flat
    m: jnp.ndarray,            # [n] slot-1 state (ignored for sgd)
    v: jnp.ndarray,            # [n] slot-2 state (adam only)
    t: jnp.ndarray,            # scalar step count AFTER this update (>= 1)
    hyper: Dict[str, jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (new_params, new_m, new_v). ``hyper``: lr (required),
    beta1/beta2/eps (adam, defaulted), mu (momentum), rho (rmsprop).
    Slot-1 meaning per optimizer: momentum=velocity, adagrad=sum of
    squared grads, rmsprop=EMA of squared grads."""
    lr = hyper["lr"]
    if name == "sgd":
        return params - lr * grads, m, v
    if name == "momentum":
        mu = hyper.get("mu", 0.9)
        new_m = mu * m + grads
        return params - lr * new_m, new_m, v
    if name == "adagrad":
        eps = hyper.get("eps", 1e-8)
        new_m = m + grads * grads
        return params - lr * grads / (jnp.sqrt(new_m) + eps), new_m, v
    if name == "rmsprop":
        rho = hyper.get("rho", 0.9)
        eps = hyper.get("eps", 1e-8)
        new_m = rho * m + (1 - rho) * grads * grads
        return params - lr * grads / (jnp.sqrt(new_m) + eps), new_m, v
    if name == "adam":
        b1 = hyper.get("beta1", 0.9)
        b2 = hyper.get("beta2", 0.999)
        eps = hyper.get("eps", 1e-8)
        new_m = b1 * m + (1 - b1) * grads
        new_v = b2 * v + (1 - b2) * grads * grads
        mhat = new_m / (1 - b1 ** t)
        vhat = new_v / (1 - b2 ** t)
        return params - lr * mhat / (jnp.sqrt(vhat) + eps), new_m, new_v
    raise ValueError(f"unknown optimizer {name!r}")
