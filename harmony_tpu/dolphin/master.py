"""Dolphin master-side control: SSP gate, lifecycle barriers, progress.

Rebuilds the reference's master components (SURVEY.md §2.6):

  * MiniBatchController  — SSP bounded staleness: each worker announces
    every mini-batch start; any worker more than ``clock_slack`` batches
    ahead of the globally slowest is blocked; a global batch budget
    (num_epochs x num_mini_batches per worker) triggers a broadcast stop
    (ref: dolphin/core/master/MiniBatchController.java:28-118).
  * WorkerStateManager   — barrier for the worker lifecycle INIT->RUN->
    CLEANUP driven by sync messages, released by broadcast
    (ref: core/master/WorkerStateManager.java:40-95).
  * BatchProgressTracker — per-worker batch index for job-level progress
    and the starting epoch on restart
    (ref: core/master/BatchProgressTracker.java).

These are in-process (condition variables instead of avro SyncMsg /
MiniBatchSyncMsg round-trips): the single-controller TPU runtime has master
and workers in one process, so "messages" are method calls; the method
surface mirrors the message vocabulary so a multi-host transport can slot in
behind the same API.

Clock-slack = 0 degrades to BSP; the SPMD fused path is the slack-0 fast
lane where the barrier is the lockstep collective itself.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional, Set


class BatchProgressTracker:
    """Tracks per-worker mini-batch progress (max batch index seen).

    ``floor_batch`` seeds the global minimum for RESUMED jobs (chain
    auto-resume, elastic recovery): a fresh tracker reporting progress 0
    would let the pod plan-horizon check accept a reshard/fence epoch
    BEHIND the continuation's real progress — the divergent-application
    hazard the horizon exists to prevent. The floor never decreases
    observed progress, only prevents understating it."""

    def __init__(self, num_mini_batches_per_epoch: int,
                 floor_batch: int = 0) -> None:
        self._nb = num_mini_batches_per_epoch
        self._floor = max(0, int(floor_batch))
        self._lock = threading.Lock()
        self._progress: Dict[str, int] = {}

    def on_batch(self, worker_id: str, global_batch_idx: int) -> None:
        with self._lock:
            cur = self._progress.get(worker_id, -1)
            if global_batch_idx > cur:
                self._progress[worker_id] = global_batch_idx

    def global_min_batch(self) -> int:
        with self._lock:
            low = min(self._progress.values()) if self._progress else 0
            return max(low, self._floor)

    def starting_epoch(self) -> int:
        """Epoch a restarted worker should resume from (ref: StartingEpochIdx
        fed by the tracker, DolphinMaster.java:116)."""
        return self.global_min_batch() // self._nb


class MiniBatchController:
    """SSP gate + global batch budget.

    Workers call :meth:`on_sync` at each batch start (the MiniBatchSyncMsg).
    The call blocks while the caller is more than ``clock_slack`` batches
    ahead of the slowest registered worker, and returns ``True`` when the
    job's batch budget is exhausted (the MiniBatchControlMsg stop
    broadcast).
    """

    def __init__(
        self,
        clock_slack: int,
        batches_per_worker: int,
        tracker: Optional[BatchProgressTracker] = None,
    ) -> None:
        self.clock_slack = clock_slack
        self.batches_per_worker = batches_per_worker
        self._cond = threading.Condition()
        self._progress: Dict[str, int] = {}
        self._stopped = False
        self._tracker = tracker

    # -- membership (elasticity adjusts this; ref: WorkerStateManager
    # keeping barrier counts consistent across reconfigurations) ---------

    def register_worker(self, worker_id: str) -> None:
        with self._cond:
            self._progress.setdefault(worker_id, 0)
            self._cond.notify_all()

    def deregister_worker(self, worker_id: str) -> None:
        """A finished/removed worker must not gate the others."""
        with self._cond:
            self._progress.pop(worker_id, None)
            self._cond.notify_all()

    def request_stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    @property
    def stopped(self) -> bool:
        with self._cond:
            return self._stopped

    # -- the gate --------------------------------------------------------

    def on_sync(self, worker_id: str, batch_idx: int) -> bool:
        """Announce batch start; block per SSP; return stop flag."""
        with self._cond:
            if worker_id not in self._progress:
                self._progress[worker_id] = 0
            self._progress[worker_id] = batch_idx
            if self._tracker is not None:
                self._tracker.on_batch(worker_id, batch_idx)
            self._cond.notify_all()
            if batch_idx >= self.batches_per_worker:
                self._stopped = True
                self._cond.notify_all()
                return True
            while (
                not self._stopped
                and self._progress
                and batch_idx > min(self._progress.values()) + self.clock_slack
            ):
                self._cond.wait()
            return self._stopped

    def make_barrier(self, worker_id: str) -> Callable[[int], bool]:
        """Worker-side MiniBatchBarrier bound to this controller (ref:
        core/worker/MiniBatchBarrier.java:28-60) — plugs into
        WorkerTasklet(batch_barrier=...)."""
        self.register_worker(worker_id)
        return lambda batch_idx: self.on_sync(worker_id, batch_idx)


class DispatchTurnstile:
    """Deterministic cyclic admission of worker dispatch turns — what makes
    multi-worker SSP legal on a MULTI-PROCESS pod.

    The hazard: a pod job's worker threads dispatch global SPMD programs,
    and every process must enqueue them in the SAME order (an inversion
    wedges the collectives — parallel/dispatch.py). Thread timing differs
    per host, so the order must come from a schedule, not the OS. The
    turnstile admits exactly one worker "turn" at a time, cycling the
    worker list in fixed order; every process runs the same cycle, so
    batch dispatches, metric drains and probes enqueue identically
    everywhere — and the per-process MiniBatchControllers see sync calls
    in the same order too, making their stop decisions deterministic
    (the reference reaches the same property by centralizing the decision
    in one master and broadcasting it, MiniBatchController.java:28-118;
    here determinism-by-schedule needs no message round-trip per batch).

    Divergence between workers is bounded by one turn, so an SSP gate with
    clock_slack >= 1 never blocks INSIDE a turn (a blocked turn-holder
    would stall the cycle); the entity clamps the slack accordingly.
    Workers that finish or die ``leave()`` so the cycle skips them.
    """

    def __init__(self, worker_ids: List[str]) -> None:
        self._order = list(worker_ids)
        self._cond = threading.Condition()
        self._pos = 0
        self._active: Set[str] = set(worker_ids)

    def _current_locked(self) -> Optional[str]:
        n = len(self._order)
        for _ in range(n):
            wid = self._order[self._pos % n]
            if wid in self._active:
                return wid
            self._pos += 1
        return None

    @contextlib.contextmanager
    def turn(self, worker_id: str):
        """Block until it is ``worker_id``'s turn; the turn ends (and the
        cycle advances) when the with-block exits."""
        with self._cond:
            self._cond.wait_for(lambda: self._current_locked() == worker_id)
        try:
            yield
        finally:
            with self._cond:
                self._pos += 1
                self._cond.notify_all()

    def leave(self, worker_id: str) -> None:
        with self._cond:
            self._active.discard(worker_id)
            self._cond.notify_all()


class WorkerStateManager:
    """Lifecycle barrier: all workers must reach a state before any proceeds.

    Worker side calls :meth:`await_barrier(worker_id, state)` (the SyncMsg);
    when every registered worker has arrived, the master releases all (the
    broadcast release). States progress INIT -> RUN -> CLEANUP.
    """

    STATES = ("INIT", "RUN", "CLEANUP")

    def __init__(self, worker_ids: List[str]) -> None:
        self._cond = threading.Condition()
        self._workers: Set[str] = set(worker_ids)
        self._arrived: Dict[str, Set[str]] = {s: set() for s in self.STATES}
        self._released: Set[str] = set()

    def update_workers(self, worker_ids: List[str]) -> None:
        """Reconfiguration: adjust the barrier membership (ref:
        ETTaskRunner.updateExecutorEntry keeping barrier counts right)."""
        with self._cond:
            self._workers = set(worker_ids)
            self._maybe_release_locked()

    def await_barrier(self, worker_id: str, state: str, timeout: Optional[float] = None) -> bool:
        if state not in self.STATES:
            raise ValueError(f"unknown state {state!r}")
        with self._cond:
            self._arrived[state].add(worker_id)
            self._maybe_release_locked()
            return self._cond.wait_for(lambda: state in self._released, timeout=timeout)

    def _maybe_release_locked(self) -> None:
        for s in self.STATES:
            if s not in self._released and self._workers and self._workers <= self._arrived[s]:
                self._released.add(s)
                self._cond.notify_all()
