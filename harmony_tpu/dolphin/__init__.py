from harmony_tpu.dolphin.trainer import Trainer, TrainerContext
from harmony_tpu.dolphin.data import TrainingDataProvider
from harmony_tpu.dolphin.accessor import ModelAccessor
from harmony_tpu.dolphin.worker import WorkerTasklet

__all__ = [
    "Trainer",
    "TrainerContext",
    "TrainingDataProvider",
    "ModelAccessor",
    "WorkerTasklet",
]
