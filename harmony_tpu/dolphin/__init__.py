"""Dolphin — the PS-style training framework layer.

Exports resolve lazily (PEP 562): ``harmony_tpu.dolphin.data`` is pure
numpy and is imported by the standalone input-worker process
(``python -m harmony_tpu.inputsvc``), which must not pay — or depend on
— the jax import the worker/accessor modules pull in. Eager ``from
harmony_tpu.dolphin import WorkerTasklet`` style imports behave exactly
as before.
"""
from typing import TYPE_CHECKING

_EXPORTS = {
    "Trainer": "harmony_tpu.dolphin.trainer",
    "TrainerContext": "harmony_tpu.dolphin.trainer",
    "TrainingDataProvider": "harmony_tpu.dolphin.data",
    "DeferredTrainingDataProvider": "harmony_tpu.dolphin.data",
    "CachedModelAccessor": "harmony_tpu.dolphin.accessor",
    "ModelAccessor": "harmony_tpu.dolphin.accessor",
    "make_accessor": "harmony_tpu.dolphin.accessor",
    "PrefetchPipeline": "harmony_tpu.dolphin.prefetch",
    "StagedBatch": "harmony_tpu.dolphin.prefetch",
    "FusedSparseStep": "harmony_tpu.dolphin.worker",
    "WorkerTasklet": "harmony_tpu.dolphin.worker",
}

__all__ = sorted(_EXPORTS)

if TYPE_CHECKING:  # pragma: no cover - static analysis only
    from harmony_tpu.dolphin.accessor import (
        CachedModelAccessor,
        ModelAccessor,
        make_accessor,
    )
    from harmony_tpu.dolphin.data import (
        DeferredTrainingDataProvider,
        TrainingDataProvider,
    )
    from harmony_tpu.dolphin.prefetch import PrefetchPipeline, StagedBatch
    from harmony_tpu.dolphin.trainer import Trainer, TrainerContext
    from harmony_tpu.dolphin.worker import FusedSparseStep, WorkerTasklet


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(mod), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
