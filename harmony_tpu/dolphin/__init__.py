from harmony_tpu.dolphin.trainer import Trainer, TrainerContext
from harmony_tpu.dolphin.data import TrainingDataProvider
from harmony_tpu.dolphin.accessor import (
    CachedModelAccessor,
    ModelAccessor,
    make_accessor,
)
from harmony_tpu.dolphin.prefetch import PrefetchPipeline, StagedBatch
from harmony_tpu.dolphin.worker import FusedSparseStep, WorkerTasklet

__all__ = [
    "Trainer",
    "TrainerContext",
    "TrainingDataProvider",
    "ModelAccessor",
    "CachedModelAccessor",
    "make_accessor",
    "FusedSparseStep",
    "PrefetchPipeline",
    "StagedBatch",
    "WorkerTasklet",
]
