"""ModelAccessor — push/pull facade with phase timing.

Parity with the reference's ModelAccessor / ETModelAccessor (dolphin/core/
worker/ModelAccessor.java:29-77, ETModelAccessor.java:43-157): pull =
getOrInit against the model table, push = update, with pull/push tracers
feeding metrics (totalPullTimeSec/totalPushTimeSec, the numbers BASELINE.md
says become all-gather / reduce-scatter time on TPU).

Used by the host-driven (irregular/sparse) path. The dense SPMD fast path
fuses pull+push into the jitted step (see worker.py) and charges the whole
step to COMP — the accessor still reports zeros for pull/push then, matching
how a fused step genuinely has no separable phases.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from harmony_tpu.metrics.tracer import Tracer
from harmony_tpu.table.table import DenseTable


class ModelAccessor:
    def __init__(self, table: DenseTable) -> None:
        self._table = table
        self.pull_tracer = Tracer()
        self.push_tracer = Tracer()

    def pull(self, keys: Sequence[int]) -> np.ndarray:
        self.pull_tracer.start()
        vals = self._table.multi_get_or_init(keys)
        self.pull_tracer.record(len(keys), block_on=None)
        return vals

    def pull_all(self) -> np.ndarray:
        self.pull_tracer.start()
        arr = self._table.pull_array()
        out = np.asarray(arr)
        self.pull_tracer.record(out.shape[0], block_on=None)
        return out

    def push(self, keys: Sequence[int], deltas: np.ndarray) -> None:
        self.push_tracer.start()
        self._table.multi_update(keys, deltas)
        self.push_tracer.record(len(keys))

    def get_and_reset_times(self) -> tuple:
        pull, push = self.pull_tracer.total_sec, self.push_tracer.total_sec
        self.pull_tracer.reset()
        self.push_tracer.reset()
        return pull, push
