"""ModelAccessor — push/pull facade with phase timing.

Parity with the reference's ModelAccessor / ETModelAccessor (dolphin/core/
worker/ModelAccessor.java:29-77, ETModelAccessor.java:43-157): pull =
getOrInit against the model table, push = update, with pull/push tracers
feeding metrics (totalPullTimeSec/totalPushTimeSec, the numbers BASELINE.md
says become all-gather / reduce-scatter time on TPU).

Used by the host-driven (irregular/sparse) path. The dense SPMD fast path
fuses pull+push into the jitted step (see worker.py) and charges the whole
step to COMP — the accessor still reports zeros for pull/push then, matching
how a fused step genuinely has no separable phases.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from harmony_tpu.metrics.tracer import Tracer
from harmony_tpu.table.table import DenseTable


class ModelAccessor:
    def __init__(self, table: DenseTable) -> None:
        self._table = table
        self.pull_tracer = Tracer(instrument="accessor.pull")
        self.push_tracer = Tracer(instrument="accessor.push")

    def pull(self, keys: Sequence[int]) -> np.ndarray:
        self.pull_tracer.start()
        vals = self._table.multi_get_or_init(keys)
        self.pull_tracer.record(len(keys), block_on=None)
        return vals

    def pull_all(self) -> np.ndarray:
        self.pull_tracer.start()
        arr = self._table.pull_array()
        out = np.asarray(arr)
        self.pull_tracer.record(out.shape[0], block_on=None)
        return out

    def push(self, keys: Sequence[int], deltas: np.ndarray) -> None:
        self.push_tracer.start()
        self._table.multi_update(keys, deltas)
        self.push_tracer.record(len(keys))

    def fused_step(self, compute_fn, **kw) -> "Any":
        """Compile this accessor's pull→compute→push cycle into ONE
        donated-buffer program (dolphin.worker.FusedSparseStep). Phase
        charging follows the module docstring's fused contract: the whole
        step is COMP (the step's own ``comp_tracer``); this accessor's
        pull/push tracers keep reporting zero — a fused step genuinely
        has no separable phases. Keyword args pass through
        (``signature=`` opts into the process program cache)."""
        from harmony_tpu.dolphin.worker import FusedSparseStep

        return FusedSparseStep(self._table, compute_fn, **kw)

    def async_step(self, compute_fn, *, staleness_bound: int = 0,
                   signature: "Any" = None) -> "Any":
        """Bounded-staleness variant of :meth:`fused_step`
        (dolphin.worker.AsyncStepDriver): the returned driver's
        ``submit(*operands)`` computes against a published model view on
        the calling thread while the PREVIOUS step's push+pull runs on a
        comm thread, blocking only when the applied-update lag would
        exceed ``staleness_bound`` (0 = fully serialized, bit-identical
        to the synchronous per-phase cycle). Comm seconds are measured
        on the driver's comm thread and surfaced via its
        ``mean_phase_seconds``/``staleness_stats`` — an overlapped phase
        is still a phase, never hidden — so this accessor's pull/push
        tracers keep reporting zero, like the fused path. ``drain()`` is
        the fence (every submitted delta applied, errors re-raised);
        call it before any host read of the table. See
        docs/DEVICE_HOT_PATH.md §Async step mode."""
        from harmony_tpu.dolphin.worker import accessor_async_step

        return accessor_async_step(self._table, compute_fn,
                                   staleness_bound=staleness_bound,
                                   signature=signature)

    def get_and_reset_times(self) -> tuple:
        pull, push = self.pull_tracer.total_sec, self.push_tracer.total_sec
        self.pull_tracer.reset()
        self.push_tracer.reset()
        return pull, push


class CachedModelAccessor(ModelAccessor):
    """Worker-side model cache with background refresh.

    Parity with the reference's CachedModelAccessor (dolphin/core/worker/
    CachedModelAccessor.java:40-75): a loading cache over the model table —
    pull hits the cache (loading misses from the table), push applies the
    update to the cache locally AND to the table remotely, and a background
    refresher re-pulls every cached key each ``refresh_period_sec`` so cached
    values track other workers' pushes. Selected by ModelCacheEnabled
    (ETDolphinLauncher.java picks the accessor class; here
    ``TrainerParams.model_cache_enabled`` via :func:`make_accessor`).

    The cache trades staleness for latency exactly like the reference: reads
    between refreshes can miss other workers' pushes, which is the same
    bounded-staleness contract SSP already admits.
    """

    def __init__(self, table: DenseTable, refresh_period_sec: float = 0.5) -> None:
        super().__init__(table)
        import threading

        self._cache: dict[int, np.ndarray] = {}
        # Per-key write version: refresh_now only installs a fetched value if
        # no local push landed between its (unlocked) table read and its
        # install — otherwise a pre-push table snapshot would overwrite the
        # just-pushed cache entry and break read-your-own-push.
        self._versions: dict[int, int] = {}
        self._cache_lock = threading.Lock()
        self._refresh_period = refresh_period_sec
        self._stop = threading.Event()
        self._refresher: threading.Thread | None = None
        if refresh_period_sec > 0:
            self._refresher = threading.Thread(
                target=self._refresh_loop, name="model-cache-refresh", daemon=True
            )
            self._refresher.start()

    # -- cache plumbing --------------------------------------------------

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._refresh_period):
            self.refresh_now()

    def refresh_now(self) -> None:
        """Re-pull every cached key (ref: the background refresh executor
        pulling all cached keys each period). Also callable directly by
        tests/apps that want deterministic refresh points."""
        with self._cache_lock:
            keys = sorted(self._cache)
            versions = {k: self._versions.get(k, 0) for k in keys}
        if not keys:
            return
        fresh = self._table.multi_get_or_init(keys)
        with self._cache_lock:
            for k, v in zip(keys, fresh):
                if self._versions.get(k, 0) == versions[k]:
                    self._cache[k] = v
                # else: a push raced this refresh; keep the newer local value
                # (the NEXT refresh re-pulls it, post-push, from the table).

    def close(self) -> None:
        self._stop.set()
        if self._refresher is not None:
            self._refresher.join(timeout=2.0)

    # -- accessor surface ------------------------------------------------

    def pull(self, keys: Sequence[int]) -> np.ndarray:
        self.pull_tracer.start()
        if not len(keys):  # np.stack rejects empty; match base-class shape
            out = np.asarray(self._table.multi_get_or_init([]))
            self.pull_tracer.record(0, block_on=None)
            return out
        with self._cache_lock:
            missing = [k for k in keys if k not in self._cache]
            versions = {k: self._versions.get(k, 0) for k in missing}
        overlay = {}
        if missing:
            loaded = self._table.multi_get_or_init(missing)
            with self._cache_lock:
                for k, v in zip(missing, loaded):
                    # Same version guard as refresh_now: if a push raced the
                    # load, the table snapshot may predate that push, and
                    # caching it would hide the pusher's write from later
                    # pulls. Serve it for THIS call only (overlay) and leave
                    # the key uncached so the next pull re-reads post-push
                    # table state.
                    if self._versions.get(k, 0) == versions[k]:
                        self._cache[k] = v
                    else:
                        overlay[k] = v
        with self._cache_lock:
            out = np.stack([
                self._cache.get(k, overlay.get(k)) if k in overlay else self._cache[k]
                for k in keys
            ])
        self.pull_tracer.record(len(keys), block_on=None)
        return out

    def push(self, keys: Sequence[int], deltas: np.ndarray) -> None:
        self.push_tracer.start()
        # Local apply first (cache sees own push immediately)…
        apply = self._table.spec.update_fn.apply
        with self._cache_lock:
            for k, d in zip(keys, np.asarray(deltas)):
                self._versions[k] = self._versions.get(k, 0) + 1
                if k in self._cache:
                    self._cache[k] = np.asarray(apply(self._cache[k], d))
        # …then the remote apply through the table (the authoritative copy).
        self._table.multi_update(keys, deltas)
        self.push_tracer.record(len(keys))


def make_accessor(table: DenseTable, model_cache_enabled: bool = False,
                  refresh_period_sec: float = 0.5) -> ModelAccessor:
    """Accessor factory keyed by ModelCacheEnabled (ref: ETDolphinLauncher
    binding CachedModelAccessor vs ETModelAccessor)."""
    if model_cache_enabled:
        return CachedModelAccessor(table, refresh_period_sec)
    return ModelAccessor(table)
