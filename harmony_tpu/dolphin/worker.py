"""WorkerTasklet — the training hot loop.

Parity with the reference's WorkerTasklet (dolphin/core/worker/
WorkerTasklet.java:96-168): per epoch, per mini-batch the phases

    SYNC  (mini-batch barrier, SSP gate)     -> barrier object
    PULL  (model pull)                        \
    COMP  (trainer local compute)              > ONE fused jitted SPMD step
    PUSH  (push updates)                      /

with per-batch and per-epoch metrics (WorkerTasklet.java:194-229).

TPU-first: the three data phases compile into a single XLA program over the
job's mesh — pull is the all-gather of the model-axis-sharded table, compute
is MXU math over the data-axis-sharded batch, push is the delta fold whose
batch-axis contraction XLA lowers to a cross-chip reduction. When no host
decision is needed between batches, the WHOLE epoch further fuses into one
``lax.scan`` dispatch (removes per-step host round-trips — measured 7x
throughput on a remote-attached chip).

Steps are dispatched through ``DenseTable.apply_step`` so buffer donation
stays invisible to concurrent host accessors, and hyper-parameters enter the
step as arguments so per-epoch decay reaches the compiled program.

Phase boundaries still exist for scheduling: each batch announces its
TaskUnits to the (optional) TaskUnit scheduler so concurrent jobs interleave
compute-heavy and network-heavy spans (ref: LocalTaskUnitScheduler.java:
83-102) — in fused mode the whole step is announced as COMP.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from harmony_tpu import faults
from harmony_tpu.data import devcache
from harmony_tpu.data.loader import StageRing
from harmony_tpu.dolphin.data import TrainingDataProvider
from harmony_tpu.dolphin.prefetch import PrefetchPipeline, StagedBatch
from harmony_tpu.dolphin.trainer import Trainer, TrainerContext
from harmony_tpu.metrics.collector import (
    BatchMetrics,
    EpochMetrics,
    InputPipelineMetrics,
    MetricCollector,
)
from harmony_tpu.parallel.dispatch import dispatch_scope
from harmony_tpu.parallel.mesh import DATA_AXIS
from harmony_tpu.runtime import progcache
from harmony_tpu.tracing import SpanContext, trace_span
from harmony_tpu.tracing.profiler import maybe_profile_epoch
from harmony_tpu.utils.platform import hard_sync


def _phase_boundary(tree, replicate_on: "Optional[Mesh]" = None):
    """Materialization point between the fused step's PULL/COMP/PUSH
    stages (``lax.optimization_barrier``): XLA must not fuse across it, so
    each stage computes exactly what its standalone program computes and
    the fused/unfused A-B arms stay BIT-identical (cross-phase fusion
    re-associates matmul accumulations — measured ~1e-7 loss drift).
    ``replicate_on`` additionally pins the boundary value replicated on
    that mesh — the PULL stage's documented contract (pull IS the
    all-gather of the model-axis-sharded table; the host-driven path
    materializes exactly this replica), without which GSPMD partitions
    the downstream compute differently per mode and reduction orders
    drift. On TPU the stages already end at Pallas kernel calls
    (ops/sparse.py), which are materialization boundaries anyway — the
    barrier codifies the contract rather than adding cost."""
    if replicate_on is not None:
        tree = _replicated_tree(tree, replicate_on)
    return jax.lax.optimization_barrier(tree)


def _replicated_tree(tree, mesh: Mesh):
    """Constrain every array leaf replicated on ``mesh`` — the boundary
    sharding both step modes share (see _phase_boundary): GSPMD
    propagates shardings backward through unconstrained values, so a
    phase-crossing value left natural partitions its producing reduction
    differently in the one-program and per-program builds, and float
    accumulation orders drift."""
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, rep), tree
    )


class _UnfusedStep:
    """The host-driven per-phase step (``TrainerParams.fused_step=False``).

    Dispatches PULL, COMP and PUSH as three separate compiled programs
    with the MODEL traffic round-tripping through host numpy between
    phases — the reference's ModelAccessor shape (pull -> host -> local
    compute -> host -> push). Worker-LOCAL table state stays on device in
    both modes (it is worker-private memory in the reference too); only
    the PS-table traffic crosses the host. Plugs into the same
    apply_step/commit machinery as the fused jit (it is just a callable),
    and only its PUSH program donates the table buffer(s), so the commit
    contract is unchanged.

    Phase seconds are measured directly (perf_counter around each
    hard-synced dispatch) and exposed via :meth:`mean_phase_seconds` —
    the worker feeds them to BatchMetrics instead of the fused path's
    probe-derived split. The FIRST call per build is excluded from the
    accumulators: it compiles the three phase programs inside the timed
    regions, and a compile-inflated mean would misattribute later
    (compile-free) batches' comp time to comm — the same reason the comm
    probe warms up before it measures.
    """

    def __init__(self, pull_p, comp_p, push_p, *, is_hash: bool,
                 uses_local: bool, keys_push: bool, replicated) -> None:
        self._pull_p = pull_p
        self._comp_p = comp_p
        self._push_p = push_p
        self._is_hash = is_hash
        self._uses_local = uses_local
        self._keys_push = keys_push
        self._replicated = replicated
        self.pull_sec = 0.0
        self.comp_sec = 0.0
        self.push_sec = 0.0
        self.steps = 0
        self.timed_steps = 0

    def mean_phase_seconds(self) -> Tuple[float, float, float]:
        """(pull, comp, push) mean device+round-trip seconds per
        steady-state step (the compile-bearing first call excluded)."""
        n = max(self.timed_steps, 1)
        return self.pull_sec / n, self.comp_sec / n, self.push_sec / n

    def _roundtrip(self, value):
        """Host round-trip of one phase boundary: D2H materialize, then
        re-place replicated on the step's mesh (a raw uncommitted upload
        racing the sharded batch operands would raise a device mismatch
        inside the next phase's program)."""
        import jax as _jax

        host = np.asarray(value)
        return _jax.device_put(host, self._replicated)

    def __call__(self, *args):
        if self._uses_local:
            arr, larr, batch, hyper = args
        else:
            arr, batch, hyper = args
            larr = None
        t0 = time.perf_counter()
        if self._is_hash:
            if self._uses_local:
                state2, rows, token, lmodel = hard_sync(
                    self._pull_p(arr, larr, batch))
            else:
                state2, rows, token = hard_sync(self._pull_p(arr, batch))
                lmodel = None
            p_t = time.perf_counter() - t0
            rows_d = self._roundtrip(rows)
            t0 = time.perf_counter()
            if self._uses_local:
                delta, new_l, metrics = hard_sync(
                    self._comp_p(rows_d, lmodel, batch, hyper))
            else:
                delta, metrics = hard_sync(
                    self._comp_p(rows_d, batch, hyper))
                new_l = None
            c_t = time.perf_counter() - t0
            delta_d = self._roundtrip(delta)
            t0 = time.perf_counter()
            if self._uses_local:
                (new_state, new_larr), dropped = hard_sync(
                    self._push_p(state2, larr, token, delta_d, new_l))
            else:
                new_state, dropped = hard_sync(
                    self._push_p(state2, token, delta_d))
                new_larr = None
            u_t = time.perf_counter() - t0
            metrics = dict(metrics)
            metrics["_dropped"] = dropped
        else:
            if self._uses_local:
                model, lmodel = hard_sync(self._pull_p(arr, larr))
            else:
                model = hard_sync(self._pull_p(arr))
                lmodel = None
            p_t = time.perf_counter() - t0
            model_d = self._roundtrip(model)
            t0 = time.perf_counter()
            if self._uses_local:
                delta, new_l, metrics = hard_sync(
                    self._comp_p(model_d, lmodel, batch, hyper))
            else:
                delta, metrics = hard_sync(
                    self._comp_p(model_d, batch, hyper))
                new_l = None
            c_t = time.perf_counter() - t0
            delta_d = self._roundtrip(delta)
            t0 = time.perf_counter()
            if self._uses_local:
                (new_arr, new_larr), sync = hard_sync(
                    self._push_p(arr, larr, delta_d, new_l))
            elif self._keys_push:
                new_arr, sync = hard_sync(self._push_p(arr, batch, delta_d))
                new_larr = None
            else:
                new_arr, sync = hard_sync(self._push_p(arr, delta_d))
                new_larr = None
            u_t = time.perf_counter() - t0
            metrics = dict(metrics)
            if not metrics:
                # same guarantee as the fused path's _with_sync: at least
                # one step-output-dependent metric (sync is one pushed
                # element, computed inside the push program)
                metrics = {"_sync": sync}
            new_state = new_arr
        if self.steps > 0:
            # steady-state only: call 0 compiled the phase programs inside
            # the timed regions (see class docstring)
            self.pull_sec += p_t
            self.comp_sec += c_t
            self.push_sec += u_t
            self.timed_steps += 1
        self.steps += 1
        if self._uses_local:
            return (new_state, new_larr), metrics
        return new_state, metrics


class AsyncStepDriver:
    """Bounded-staleness async aggregation (``TrainerParams.async_step``).

    Wraps the unfused per-phase programs (same traced math, same host
    round-trip boundaries — see :class:`_UnfusedStep`) but moves the
    PUSH+PULL comm phases onto a dedicated comm thread so they overlap
    the NEXT step's COMP on the training thread::

        train thread:  COMP(k) on view v_k -> submit delta_k -> COMP(k+1)
        comm thread:   PUSH(delta_k) ; PULL -> publish view k+1

    Deltas ride a FIFO :class:`~harmony_tpu.data.loader.StageRing` with
    a single consumer, so the table's update sequence is a deterministic
    function of (seed, epoch, step-apply-order) — submission order IS
    apply order, which is the replay contract elastic recovery depends
    on. ``staleness_bound`` caps the applied-update lag a compute step
    may observe: COMP for step k hard-blocks until the published view
    reflects at least ``k - bound`` applied deltas. Bound 0 fully
    serializes the pipeline and is BIT-identical to the synchronous
    per-phase path (identical programs, identical round-trips, identical
    apply order — pinned by tests/test_async_step.py; the per-phase path
    is in turn pinned bit-identical to the fused step).

    ``drain()`` is the fence: it blocks until every submitted delta is
    applied and the post-apply view is published, re-raising any
    comm-thread failure. The worker drains at every epoch boundary
    (before metric drains, snapshots, trainer hooks) and before program
    rebuilds, so elastic fences always observe an empty in-flight
    window.

    Comm seconds are measured ON the comm thread (they are real wire
    time, merely overlapped) and exposed via :meth:`mean_phase_seconds`
    exactly like _UnfusedStep's — the phase budget attributes them to
    pull_comm/push_comm honestly instead of hiding the overlap;
    :meth:`staleness_stats` additionally reports the exposed
    (compute-blocking) wait so ``obs critpath``/the dashboard can show
    overlapped vs exposed comm time.
    """

    #: comm-thread join grace on teardown (the prefetch pipeline's bound)
    JOIN_TIMEOUT = 10.0

    def __init__(self, inner: _UnfusedStep, *, bound: int, model_table,
                 local_table=None, mesh: Mesh, job_id: str = "",
                 worker_id: str = "") -> None:
        if inner._is_hash or inner._keys_push:
            raise ValueError(
                "async step mode drives dense pull_mode='all' tables only "
                "(a keys-mode pull depends on the batch, and the published-"
                "view pipeline has no batch yet when it pulls)")
        self._pull_p = inner._pull_p
        self._comp_p = inner._comp_p
        self._push_p = inner._push_p
        self._uses_local = inner._uses_local
        self._replicated = inner._replicated
        self._bound = max(0, int(bound))
        self._table = model_table
        self._local = local_table
        self._mesh = mesh
        self._job_id = job_id
        self._worker_id = worker_id
        # Publication state: _version counts deltas REFLECTED in the
        # published (model, lmodel) view, _applied counts deltas the comm
        # thread has pushed. One condition guards both plus the error
        # slot — StageRing.set_error flows producer->consumer, the wrong
        # direction for comm-thread failures.
        self._cond = threading.Condition()
        self._version = -1  # -1 = initial view not yet published
        self._applied = 0
        self._submitted = 0
        self._view: Optional[Tuple[Any, Any]] = None
        self._err: Optional[BaseException] = None
        # The in-flight delta window rides the shared staging primitive
        # (the dolphin/prefetch.py precedent). The staleness gate in
        # submit() is the real bound; the cap just keeps the ring honest.
        self._ring = StageRing(cap_fn=lambda: self._bound + 1)
        self._thread: Optional[threading.Thread] = None
        # Phase accounting, _UnfusedStep's contract: the compile-bearing
        # first step is excluded from every accumulator.
        self.pull_sec = 0.0
        self.comp_sec = 0.0
        self.push_sec = 0.0
        self.steps = 0
        self.timed_steps = 0
        self._comm_steps = 0
        # staleness telemetry (tenant ledger + dashboards)
        self.max_lag = 0
        self.exposed_wait_sec = 0.0

    def _roundtrip(self, value):
        """Host round-trip of one phase boundary (see
        _UnfusedStep._roundtrip — identical placement so bound 0 stays
        bit-identical to the per-phase path)."""
        host = np.asarray(value)
        return jax.device_put(host, self._replicated)

    def _raise_pending(self) -> None:
        with self._cond:
            err = self._err
        if err is not None:
            raise RuntimeError(
                "async step comm thread failed; the in-flight window is "
                "lost — fail this attempt (elastic recovery replays with "
                "the same apply schedule)") from err

    def _publish_initial(self) -> None:
        """View v0: one PULL of the live table — exactly where the
        synchronous step's first pull happens. Runs on the training
        thread (before the comm thread starts) through the same
        apply_step lock every table access takes."""
        from harmony_tpu.table.table import DenseTable

        if self._uses_local:
            def init_fn(arr, larr):
                model, lmodel = hard_sync(self._pull_p(arr, larr))
                return (arr, larr), (model, lmodel)

            model, lmodel = DenseTable.apply_step_multi(
                [self._table, self._local], init_fn)
        else:
            def init_fn(arr):
                return arr, hard_sync(self._pull_p(arr))

            model = self._table.apply_step(init_fn)
            lmodel = None
        model_d = self._roundtrip(model)
        with self._cond:
            self._version = 0
            self._view = (model_d, lmodel)
            self._cond.notify_all()

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._publish_initial()
            self._thread = threading.Thread(
                target=self._comm_loop,
                name=f"async-step-{self._job_id}", daemon=True)
            self._thread.start()

    def submit(self, *operands):
        """One training step: staleness gate, COMP against the published
        view, enqueue the delta for the comm thread. Returns the step's
        metrics dict (device arrays — the epoch drain stacks them)."""
        self._raise_pending()
        self._ensure_started()
        k = self._submitted
        floor = k - self._bound  # the view must reflect >= this many applies
        t0 = time.perf_counter()
        model_d = lmodel = None
        with self._cond:
            while self._err is None and self._version < max(floor, 0):
                self._cond.wait(0.05)
            if self._err is None:
                lag = k - self._version
                if lag > self.max_lag:
                    self.max_lag = lag
                model_d, lmodel = self._view
        wait_t = time.perf_counter() - t0
        self._raise_pending()
        if k > 1:
            # k=1's wait absorbs cycle 0's push/pull compile — excluded
            # for the same reason _UnfusedStep drops its first call
            self.exposed_wait_sec += wait_t
        t0 = time.perf_counter()
        # standalone dispatch (the probe's pattern): scope wraps the
        # dispatch, the sync happens outside the lock
        with dispatch_scope(self._mesh) as fin:
            if self._uses_local:
                out = fin(self._comp_p(model_d, lmodel, *operands))
            else:
                out = fin(self._comp_p(model_d, *operands))
        out = hard_sync(out)
        if self._uses_local:
            delta, new_l, metrics = out
        else:
            (delta, metrics), new_l = out, None
        c_t = time.perf_counter() - t0
        if self.steps > 0:
            self.comp_sec += c_t
            self.timed_steps += 1
        self.steps += 1
        self._submitted = k + 1
        if not self._ring.put((k, delta, new_l)):
            self._raise_pending()
            raise RuntimeError("async step ring closed mid-training")
        metrics = dict(metrics)
        if not metrics:
            # same guarantee as _UnfusedStep's _sync: at least one
            # step-output-dependent metric. The push lands later on the
            # comm thread, so the sentinel reads the delta instead of
            # the pushed array.
            leaf = jax.tree_util.tree_leaves(delta)[0]
            metrics = {"_sync": jnp.ravel(leaf)[0]}
        return metrics

    def _comm_loop(self) -> None:
        from harmony_tpu.table.table import DenseTable

        try:
            while True:
                item = self._ring.get()
                if item is StageRing.DONE:
                    return
                k, delta, new_l = item
                # The model-pull wire-time fault site rides the COMM
                # thread here: injected comm latency lands in the
                # overlapped window — exactly where real wire time
                # would — which is the async bench's A/B mechanism.
                if faults.armed():
                    faults.site("worker.pull", job=self._job_id,
                                worker=self._worker_id, batch=k, comm=1)
                timings: Dict[str, float] = {}
                delta_d = self._roundtrip(delta)
                if self._uses_local:
                    def cycle(arr, larr):
                        t1 = time.perf_counter()
                        (new_arr, new_larr), sync = hard_sync(
                            self._push_p(arr, larr, delta_d, new_l))
                        timings["push"] = time.perf_counter() - t1
                        t1 = time.perf_counter()
                        model, lm = hard_sync(
                            self._pull_p(new_arr, new_larr))
                        timings["pull"] = time.perf_counter() - t1
                        return (new_arr, new_larr), (model, lm, sync)

                    model, lmodel, _sync = DenseTable.apply_step_multi(
                        [self._table, self._local], cycle)
                else:
                    def cycle(arr):
                        t1 = time.perf_counter()
                        new_arr, sync = hard_sync(
                            self._push_p(arr, delta_d))
                        timings["push"] = time.perf_counter() - t1
                        t1 = time.perf_counter()
                        model = hard_sync(self._pull_p(new_arr))
                        timings["pull"] = time.perf_counter() - t1
                        return new_arr, (model, sync)

                    model, _sync = self._table.apply_step(cycle)
                    lmodel = None
                model_d = self._roundtrip(model)
                with self._cond:
                    self._applied = k + 1
                    self._version = k + 1
                    self._view = (model_d, lmodel)
                    if k > 0:
                        # steady-state only: cycle 0 compiles the push
                        # program inside its timed region
                        self.push_sec += timings.get("push", 0.0)
                        self.pull_sec += timings.get("pull", 0.0)
                        self._comm_steps += 1
                    self._cond.notify_all()
        except BaseException as e:  # noqa: BLE001 - re-raised on submit/drain
            with self._cond:
                self._err = e
                self._cond.notify_all()
            # unblock a producer parked in ring.put (its next put
            # returns False and submit re-raises the recorded error)
            self._ring.close()

    def mean_phase_seconds(self) -> Tuple[float, float, float]:
        """(pull, comp, push) mean seconds per steady-state step. The
        comm means are REAL wire time measured on the comm thread (they
        overlap compute — the budget attributes them honestly); comp is
        the training thread's. Compile-bearing first step excluded."""
        with self._cond:
            n_comm = max(self._comm_steps, 1)
            n_comp = max(self.timed_steps, 1)
            return (self.pull_sec / n_comm, self.comp_sec / n_comp,
                    self.push_sec / n_comm)

    def staleness_stats(self) -> Dict[str, Any]:
        """Ledger feed: bound, observed lag, exposed vs overlapped comm."""
        with self._cond:
            return {
                "bound": self._bound,
                "max_lag": int(self.max_lag),
                "exposed_wait_sec": self.exposed_wait_sec,
                "overlapped_comm_sec": self.pull_sec + self.push_sec,
                "applied": int(self._applied),
                "submitted": int(self._submitted),
            }

    def drain(self) -> None:
        """The fence: block until every submitted delta is APPLIED and
        the post-apply view published; re-raise any comm failure."""
        if self._thread is None:
            self._raise_pending()
            return
        with self._cond:
            while self._err is None and self._applied < self._submitted:
                self._cond.wait(0.05)
        self._raise_pending()

    def close(self) -> None:
        """Drain (raising on a comm failure — a rebuild must surface a
        pending error, not drop it with the old driver), then join."""
        self.drain()
        self.shutdown()

    def shutdown(self) -> None:
        """Best-effort teardown (exception/run-end path): never raises.
        The happy path drained at the last epoch boundary, so the ring
        is empty; an exception path is abandoning the attempt anyway."""
        t = self._thread
        self._thread = None
        self._ring.finish()
        self._ring.close()
        if t is not None:
            t.join(self.JOIN_TIMEOUT)


def accessor_async_step(table, compute_fn, *, staleness_bound: int = 0,
                        signature: Optional[Any] = None) -> AsyncStepDriver:
    """Bounded-staleness driver for ModelAccessor users (the host-driven
    path outside WorkerTasklet — benchmarks, apps driving a table
    directly). Builds the dense pull_all/compute/push_all phase programs
    for ``table`` (progcache-cached when ``signature`` names the
    compute_fn's traced behavior, the FusedSparseStep contract) and
    returns an :class:`AsyncStepDriver` whose ``submit(*operands)``
    overlaps the previous step's PUSH+PULL with this step's compute
    under ``staleness_bound``. ``compute_fn`` maps
    ``(model, *operands) -> delta`` or ``(delta, metrics_dict)``;
    ``drain()``/``close()`` carry the same fence contract as the worker
    path (docs/DEVICE_HOT_PATH.md §Async step mode)."""
    from harmony_tpu.table.hashtable import DeviceHashTable
    from harmony_tpu.table.table import DenseTable

    if isinstance(table, DeviceHashTable):
        raise TypeError(
            "async step drives DenseTable workloads; hash-backed tables "
            "keep the synchronous keyed step")
    if not isinstance(table, DenseTable):
        raise TypeError(f"need a DenseTable, got {type(table).__name__}")
    spec = table.spec
    mesh = table.mesh
    tsh = table.sharding
    replicated = NamedSharding(mesh, P())

    def pull_fn(arr):
        return _replicated_tree(spec.pull_all(arr), mesh)

    def comp_fn(model, *operands):
        out = compute_fn(model, *operands)
        if not (isinstance(out, tuple) and len(out) == 2
                and isinstance(out[1], dict)):
            out = (out, {})
        delta, metrics = out
        return _replicated_tree(delta, mesh), dict(metrics)

    def push_fn(arr, delta):
        new_arr = spec.push_all(arr, delta)
        return new_arr, jnp.ravel(new_arr)[0]

    def cached(tag, build):
        key = (None if signature is None else
               (("accessor_async", signature,
                 progcache.table_signature(table, sharding=tsh)), tag))
        return progcache.get_or_build(key, build)

    pull_p = cached("pull", lambda: jax.jit(pull_fn))
    comp_p = cached("comp", lambda: jax.jit(comp_fn))
    push_p = cached("push", lambda: jax.jit(push_fn, donate_argnums=(0,),
                                            out_shardings=(tsh, None)))
    inner = _UnfusedStep(pull_p, comp_p, push_p, is_hash=False,
                         uses_local=False, keys_push=False,
                         replicated=replicated)
    return AsyncStepDriver(inner, bound=staleness_bound, model_table=table,
                           mesh=mesh)


class WorkerTasklet:
    """Drives the training loop for one job over its mesh slice."""

    def __init__(
        self,
        job_id: str,
        ctx: TrainerContext,
        trainer: Trainer,
        data: TrainingDataProvider,
        mesh: Mesh,
        collector: Optional[MetricCollector] = None,
        batch_barrier: Optional[Callable[[int], bool]] = None,
        taskunit: Optional[Any] = None,
        epoch_callback: Optional[Callable[[int], None]] = None,
        starting_epoch: int = 0,
        global_init: bool = True,
        post_init_barrier: Optional[Callable[[], None]] = None,
        defer_epoch_callback: bool = False,
        dispatch_turn: Optional[Callable[[], Any]] = None,
        pending_plan_epoch: Optional[Callable[[], Optional[int]]] = None,
        pod_contended: Optional[Callable[[], bool]] = None,
        trace_parent: Optional[Dict[str, str]] = None,
        attempt: int = 0,
        input_feed: Optional[Any] = None,
    ) -> None:
        self.job_id = job_id
        # Disaggregated input service (harmony_tpu/inputsvc): a
        # TrainerInputFeed streaming assembled host batches off the
        # shared input workers, with built-in bounded retry and
        # in-process fallback. None = local assembly (the default).
        # The feed replaces WHERE host batches come from; staging,
        # devcache bypass, reshard invalidation and sharding checks are
        # untouched, and losses stay bit-identical for a fixed seed.
        self._input_feed = input_feed
        # Trace threading (tracing/span.py): the worker runs on its own
        # thread, so the entity hands the dispatch span's wire context
        # down explicitly — contextvars do not cross Thread starts. The
        # elastic attempt index keys the `attempt` label/annotation as
        # `job@aN` (jobserver/elastic.attempt_key's scheme).
        self.trace_parent = trace_parent
        self.attempt = int(attempt or 0)
        self.attempt_key = (job_id if self.attempt <= 0
                            else f"{job_id}@a{self.attempt}")
        self.ctx = ctx
        self.trainer = trainer
        self.data = data
        self.mesh = mesh
        self.collector = collector or MetricCollector()
        # batch_barrier(batch_idx) -> stop_flag (ref: MiniBatchBarrier.await
        # returning the master's stop decision, MiniBatchBarrier.java:28-60).
        self.batch_barrier = batch_barrier
        self.taskunit = taskunit
        self.epoch_callback = epoch_callback
        # True = the callback only does host accounting off already-drained
        # values (metric emission) and may run AFTER a multi-epoch fused
        # window drains, once per epoch in order. False = the callback
        # observes table state at its epoch boundary (checkpoint chains),
        # which a window would skip past — windows stay off.
        self.defer_epoch_callback = defer_epoch_callback
        self.starting_epoch = starting_epoch  # resume (ref: StartingEpochIdx)
        # Multi-worker jobs: exactly ONE worker (the chief) may run the
        # trainer's global init — it writes shared tables, and N identical
        # additive inits would accumulate N-fold (ref: initGlobalSettings is
        # a per-JOB setup). post_init_barrier makes the others wait for it.
        self.global_init = global_init
        self.post_init_barrier = post_init_barrier
        # Pod-lockstep multi-worker: a callable yielding this worker's
        # admission-turn context manager (dolphin/master.DispatchTurnstile).
        # Every multi-device dispatch this worker makes — batch steps,
        # metric drains, probes — happens inside a turn, so concurrent
        # worker threads enqueue in the SAME deterministic order on every
        # process of the pod.
        self.dispatch_turn = dispatch_turn
        # Cross-job pod tenancy: returns the contended flag of this job's
        # last COMPLETED dispatch unit (runtime/podunits.py) — a value
        # every process reads at the same logical point, so dispatch-
        # window decisions branched on it stay deterministic pod-wide.
        self.pod_contended = pod_contended
        # Pod reshard plans: callable returning the next scheduled plan
        # epoch (or None). Multi-epoch windows must END at a plan epoch so
        # its application (via the deferred epoch-hook replay) lands right
        # after that epoch's dispatches, not after the whole window.
        # Deterministic across pod processes by the scheduling contract:
        # plans carry multi-epoch lead, so by the time any process makes
        # the window decision covering the plan epoch, the plan has
        # arrived everywhere (jobserver/podplan.py).
        self.pending_plan_epoch = pending_plan_epoch
        self._pending_probe = None  # probe deferred into the 1st batch turn
        self._step = None
        self._epoch_fn = None
        self._eval_fn = None
        self._program_cache_key = None  # set by _build_step
        self._built_once = False
        # Comm/comp split probe (see _probe_comm): period in epochs; 0 = off.
        # Cadence: the split is a property of the (layout, shapes) pair, so
        # the probe runs on FIRST use and again after any rebuild/reshard
        # (which clears the programs), plus a slow drift refresh every
        # 8x period epochs — NOT every period epochs (an every-epoch probe
        # both blocked multi-epoch dispatch windows for default jobs and,
        # under multi-tenancy, serialized ~8 dispatches per epoch behind
        # other tenants' steps, dominating cheap jobs' wall time).
        self.comm_probe_every = getattr(ctx.params, "comm_probe_period", 1)
        self._next_probe = 0  # epochs-since-start of the next drift refresh
        # EWMA of own dispatch seconds per batch. None = unseeded — a
        # legitimately measured 0.0 must count as a measurement (0.0 is
        # reachable on sub-resolution timers), so seeding tests use the
        # sentinel, never truthiness.
        self._own_batch_cost: Optional[float] = None
        self._prewarmed_stacked = None  # (sharding, stacked) from prewarm
        self._probe_pull = None
        self._probe_pp = None
        self._comm_probe_times = (0.0, 0.0)
        self._step_sharding = None
        self._local_sharding = None
        self._batch_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self._batch_sig = progcache.sharding_signature(self._batch_sharding)
        # Keep device-resident copies of batches across epochs (kills the
        # per-epoch H2D re-transfer; only valid when batches are stable).
        self.cache_device_batches = not data.is_shuffling
        self._batch_cache: Dict[int, Any] = {}
        self._stacked_cache = None
        # Async input pipeline (dolphin/prefetch.py): batch assembly + H2D
        # staging on a producer thread, overlapping device compute. Config
        # default ON; _prefetch_usable() gates it off where a background
        # device_put would break pod-deterministic dispatch order.
        self._prefetch_on = bool(getattr(ctx.params, "input_prefetch", True))
        # Fused device hot path (config default ON): each batch's
        # PULL/COMP/PUSH compiles into one donated-buffer program. OFF
        # selects the unfused per-phase fallback (_build_unfused): three
        # separately-dispatched programs with a host round-trip between
        # phases — the reference's host-driven ModelAccessor shape, kept
        # as the bit-identical A/B arm and the operator rollback path.
        # HARMONY_FUSED_STEP (0/1) overrides process-wide.
        fused = bool(getattr(ctx.params, "fused_step", True))
        env_fused = os.environ.get("HARMONY_FUSED_STEP")
        if env_fused is not None:
            fused = env_fused.strip().lower() not in ("0", "false", "off")
        self._fused_on = fused
        # Bounded-staleness async aggregation (AsyncStepDriver): overlap
        # step k's PUSH+PULL with step k+1's COMP on a comm thread.
        # Default OFF preserves today's synchronous contract; the env
        # knobs are the process-wide operator override, same shape as
        # HARMONY_FUSED_STEP above. See docs/DEVICE_HOT_PATH.md.
        async_on = bool(getattr(ctx.params, "async_step", False))
        env_async = os.environ.get("HARMONY_ASYNC_STEP")
        if env_async is not None:
            async_on = env_async.strip().lower() not in ("0", "false", "off")
        self._async_on = async_on
        bound = int(getattr(ctx.params, "staleness_bound", 0) or 0)
        env_bound = os.environ.get("HARMONY_STALENESS_BOUND")
        if env_bound is not None:
            try:
                bound = int(env_bound.strip())
            except ValueError:
                pass
        self._staleness_bound = max(0, bound)
        self._active_pipeline: Optional[PrefetchPipeline] = None
        # (epoch, pipeline) spawned ahead of its epoch (see
        # _spawn_next_pipeline) — consumed by _epoch_batch_stream
        self._next_pipeline: Optional[Tuple[int, PrefetchPipeline]] = None
        # set by _on_layout_announcement when the ANNOUNCED target mesh
        # spans processes — self.mesh lags the flip, so this is what stops
        # new staging producers from spawning in the announce->flip window
        self._staging_unsafe = False
        self._prefetch_hits = 0
        self._prefetch_misses = 0
        # This worker's own op counters (single-threaded; per-job metric
        # attribution sums these across the job's workers).
        self.op_stats: Dict[str, int] = {"pulls": 0, "pushes": 0, "pull_bytes": 0}
        # Per-job throughput SLO (metrics/accounting.py): the env knob
        # overrides the per-job param for every tenant (operator floor).
        # Breach detection is chief-only and windowed: SLO_WINDOW_EPOCHS
        # consecutive epochs under 90% of target fire ONE structured
        # joblog event (kind="slo"); recovery re-arms it.
        from harmony_tpu.metrics import accounting as _acct

        target = _acct.slo_target_from_env()
        if target is None:
            p = float(getattr(ctx.params, "target_samples_per_sec", 0.0)
                      or 0.0)
            target = p if p > 0 else None
        self._slo_target: Optional[float] = target
        self._slo_below = 0
        self._slo_fired = False
        # FLOPs of one step of the CURRENT compiled program (progcache
        # cost table); resolved lazily after the first compile, reset on
        # rebuild. None = backend exposes no cost model (ledger keeps
        # the None — 0.0 is reserved for real zeros).
        self._flops_per_step: Optional[float] = None
        # Step-phase time budget (metrics/phases.py): host-dispatch
        # seconds accumulate on the training thread between "batch
        # ready" and the device dispatch call; per-epoch phase splits
        # stage in _phase_pending (keyed by epoch) between the metric
        # drain — where the work split is computed — and _finish_epoch,
        # where the epoch WALL is finally known and the budget feeds.
        self._phase_dispatch_acc = 0.0
        self._phase_pending: Dict[int, Dict[str, float]] = {}
        self._phase_input_wait: Dict[int, float] = {}

    # -- step construction ----------------------------------------------

    @staticmethod
    def _with_sync(metrics, arr):
        """Guarantee at least one step-output-dependent metric: the async
        loop's in-flight throttle blocks on metrics, so an empty dict would
        make the bound a no-op. ``_sync`` is one element of the pushed
        array (data-dependent, so XLA cannot fold it away); host-side
        consumers strip underscore-keys."""
        if metrics:
            return metrics
        return {"_sync": jnp.ravel(arr)[0]}

    def _step_core(self, push_route: str, mesh: Mesh):
        """The fused PULL/COMP/PUSH body shared by per-batch and per-epoch
        compilation. ``hyper`` is a dict of scalars (lr etc.) passed fresh
        each dispatch so host-side decay is honored. ``push_route`` is the
        RESOLVED keyed-push lowering and ``mesh`` the LAYOUT SNAPSHOT's
        mesh — both threaded from the caller so the traced program is
        fully determined by its program-cache key (reading the live
        table's mesh here let a prewarm cache a target-key program whose
        sharding constraints pinned the OLD mesh)."""
        from harmony_tpu.table.hashtable import DeviceHashTable

        spec = self.ctx.model_table.spec
        trainer = self.trainer
        sync = self._with_sync
        is_hash = isinstance(self.ctx.model_table, DeviceHashTable)

        def _hash_pull_push(state, batch, compute):
            """Shared keyed core for hash-backed model tables: getOrInit
            pull -> compute(rows) -> token push, in one fused program.

            Keys MUST be replicated before they index the table: a
            data-sharded key vector of uneven per-shard length (batch ids +
            replicated reserved rows) makes XLA's SPMD partitioner pad its
            operands, and padded lanes flow through the elementwise chain
            as phantom key-0 entries (key 0 is reserved as a second
            defense). Returns (state, compute's aux, metrics with the
            mandatory _dropped count — drops are drained into
            table.overflow_count at epoch end, never silent)."""
            replicated = NamedSharding(mesh, P())
            keys = jax.lax.with_sharding_constraint(
                trainer.pull_keys(batch), replicated
            )
            state, rows, token = spec.pull(state, keys)            # PULL
            rows = _phase_boundary(rows, replicate_on=mesh)
            delta, aux, metrics = _phase_boundary(compute(rows),
                                                  replicate_on=mesh)  # COMP
            # SPI hook (identity by default): trainers maintaining cross-row
            # invariants (e.g. LDA's summary row = sum of word rows)
            # reconcile the delta with the admission mask so a dropped
            # row's contribution drops EVERYWHERE, not just at its own slot
            delta = trainer.mask_delta(delta, token[2])
            state = spec.push(state, token, delta)                 # PUSH
            metrics = dict(metrics)
            metrics["_dropped"] = jnp.sum(~token[2]).astype(jnp.float32)
            return state, aux, metrics

        if is_hash and trainer.pull_mode != "keys":
            raise ValueError(
                "hash-backed model tables need pull_mode='keys' "
                "(pull_all over an unbounded key domain is undefined)"
            )
        if trainer.uses_local_table:
            local_spec = self.ctx.local_table.spec
            if is_hash:
                # Sparse model table beside a dense worker-local table (the
                # sparse-LDA shape: hash-backed topic-word counts, dense
                # per-doc assignments).

                def _step(state, local, batch, hyper):
                    # the local pull belongs to the PULL stage even though
                    # it is traced inside the compute closure — barrier it
                    # so the stage split matches the unfused build's
                    lmodel = _phase_boundary(local_spec.pull_all(local),
                                             replicate_on=mesh)
                    state, new_l, metrics = _hash_pull_push(
                        state,
                        batch,
                        lambda rows: trainer.compute_with_local(
                            rows, lmodel, batch, hyper
                        ),
                    )
                    return (
                        state,
                        local_spec.write_all(local, new_l),
                    ), sync(metrics, state[1])

                return _step

            def _step(arr, local, batch, hyper):
                model, lmodel = _phase_boundary(
                    (spec.pull_all(arr), local_spec.pull_all(local)),
                    replicate_on=mesh,
                )                                                  # PULL
                delta, new_l, metrics = _phase_boundary(
                    trainer.compute_with_local(model, lmodel, batch, hyper),
                    replicate_on=mesh,
                )                                                  # COMP
                new_arr = spec.push_all(arr, delta)                # PUSH
                return (
                    new_arr,
                    local_spec.write_all(local, new_l),
                ), sync(metrics, new_arr)

            return _step

        if is_hash:

            def _step(state, batch, hyper):
                def compute(rows):
                    delta, metrics = trainer.compute(rows, batch, hyper)
                    return delta, None, metrics

                state, _, metrics = _hash_pull_push(state, batch, compute)
                return state, sync(metrics, state[1])

            return _step
        if trainer.pull_mode == "all":

            def _step(arr, batch, hyper):
                model = _phase_boundary(spec.pull_all(arr),
                                        replicate_on=mesh)         # PULL
                delta, metrics = _phase_boundary(
                    trainer.compute(model, batch, hyper),
                    replicate_on=mesh)                             # COMP
                new_arr = spec.push_all(arr, delta)                # PUSH
                return new_arr, sync(metrics, new_arr)

        else:
            push_via = push_route

            def _step(arr, batch, hyper):
                keys = trainer.pull_keys(batch)
                model = _phase_boundary(spec.pull(arr, keys),
                                        replicate_on=mesh)         # PULL
                delta, metrics = _phase_boundary(
                    trainer.compute(model, batch, hyper),
                    replicate_on=mesh)                             # COMP
                new_arr = spec.push(arr, keys, delta, via=push_via)  # PUSH
                return new_arr, sync(metrics, new_arr)

        return _step

    def _resolve_push_route(self) -> str:
        """The table's keyed-push route with "mxu_auto" resolved by a
        one-time MEASUREMENT at this job's actual push shape (the static
        capacity//256 gate picked the measured-slower route on chip —
        table/autotune.py). Cached process-wide per shape signature.

        The measurement is an ad-hoc device dispatch, so the same guards
        as _prewarm_layout apply: turnstiled or multi-process meshes keep
        the static gate (shape-derived, deterministic on every process —
        a noisy local timing could bake DIFFERENT lowerings into the same
        SPMD step across processes)."""
        table = self.ctx.model_table
        via = getattr(table, "push_via", None)
        if via != "mxu_auto" or self.trainer.pull_mode != "keys":
            return via
        if (self.dispatch_turn is not None
                or self._mesh_spans_processes(table.mesh)):
            return via  # static gate resolves deterministically in-trace
        try:
            sample = tuple(
                jax.ShapeDtypeStruct((self.data.batch_size, *tail), dt)
                for tail, dt in self.data.array_specs()
            )
            nkeys = int(jax.eval_shape(self.trainer.pull_keys, sample).shape[0])
            from harmony_tpu.table.autotune import choose_push_route

            return choose_push_route(table.spec, table.mesh, nkeys,
                                     table=table)
        except Exception:
            return via  # static mxu_auto gate as the fallback

    def _program_key(self, table_sharding, local_sharding,
                     push_route) -> "tuple | None":
        """Structural signature of everything the jitted step traces, for the
        process-level program cache (runtime/progcache) — None opts out.
        Components: trainer behavior, table schema + layout SNAPSHOT (the
        same snapshot the jit out_shardings use — reading the live sharding
        twice would let a concurrent reshard poison the cache with a
        key/executable layout mismatch), batch shapes, hyper keys, and the
        dispatch shape."""
        tsig = self.trainer.jit_signature()
        if tsig is None:
            return None
        table_sig = progcache.table_signature(
            self.ctx.model_table, sharding=table_sharding
        )
        if table_sig is None:
            return None
        if self.trainer.uses_local_table:
            local_sig = progcache.table_signature(
                self.ctx.local_table, sharding=local_sharding
            )
            if local_sig is None:
                return None
        else:
            local_sig = None
        batch_sig = tuple(
            (self.data.batch_size, *tail, str(dt))
            for tail, dt in self.data.array_specs()
        )
        hyper_sig = tuple(sorted(self.trainer.hyperparams().keys()))
        return (tsig, table_sig, local_sig, batch_sig, hyper_sig,
                push_route,  # the BAKED lowering (measured; see caller)
                self.data.num_mini_batches if self._use_fused_epoch() else None,
                # fused and unfused builds trace DIFFERENT programs from
                # otherwise-identical signatures — the mode is part of the
                # structural identity
                ("async" if self._async_mode() else
                 "fused" if self._fused_mode() else "unfused"))

    def _program_builders(self, tsh, lsh, push_route):
        """The step/epoch jit-wrapper constructors for a GIVEN layout
        snapshot — shared by _build_step (live layout) and _prewarm_layout
        (announced target layout)."""
        mesh = (tsh[0] if isinstance(tsh, tuple) else tsh).mesh

        def build_step():
            step = self._step_core(push_route, mesh)
            if self.trainer.uses_local_table:
                return jax.jit(step, out_shardings=((tsh, lsh), None),
                               donate_argnums=(0, 1))
            return jax.jit(step, out_shardings=(tsh, None), donate_argnums=0)

        def build_epoch():
            step = self._step_core(push_route, mesh)
            if self.trainer.uses_local_table:

                def _epoch2(arr, larr, stacked, hyper):
                    def body(carry, b):
                        (new_pair, metrics) = step(carry[0], carry[1], b, hyper)
                        return new_pair, metrics

                    (fa, fl), ms = jax.lax.scan(body, (arr, larr), stacked)
                    return (fa, fl), ms

                return jax.jit(_epoch2, out_shardings=((tsh, lsh), None),
                               donate_argnums=(0, 1))

            def _epoch(arr, stacked, hyper):
                return jax.lax.scan(lambda a, b: step(a, b, hyper), arr, stacked)

            return jax.jit(_epoch, out_shardings=(tsh, None), donate_argnums=0)

        return build_step, build_epoch

    def _build_unfused(self, key, tsh, lsh, push_route) -> "_UnfusedStep":
        """The per-phase fallback (fused_step=False): PULL, COMP and PUSH
        as three separately-compiled programs with a host round-trip
        between phases — the reference's host-driven ModelAccessor shape
        (pull -> numpy -> compute -> numpy -> push), kept bit-identical to
        the fused program (same traced math, different dispatch
        boundaries; gathers/adds are boundary-insensitive). The phase
        programs participate in the program cache under the same
        structural key as the fused step (mode-tagged), so rebuilds and
        resubmissions reuse them. Only the PUSH program donates the table
        buffer(s) — PULL must read them first."""
        from harmony_tpu.table.hashtable import DeviceHashTable

        spec = self.ctx.model_table.spec
        trainer = self.trainer
        is_hash = isinstance(self.ctx.model_table, DeviceHashTable)
        mesh = (tsh[0] if isinstance(tsh, tuple) else tsh).mesh
        local_spec = (self.ctx.local_table.spec
                      if trainer.uses_local_table else None)
        replicated = NamedSharding(mesh, P())

        mesh2 = mesh  # the boundary-replication mesh (see _replicated_tree)
        keys_push = False
        if is_hash:
            if trainer.uses_local_table:
                def pull_fn(state, larr, batch):
                    keys = jax.lax.with_sharding_constraint(
                        trainer.pull_keys(batch), replicated
                    )
                    state2, rows, token = spec.pull(state, keys)
                    rows, lmodel = _replicated_tree(
                        (rows, local_spec.pull_all(larr)), mesh2)
                    return state2, rows, token, lmodel

                def comp_fn(rows, lmodel, batch, hyper):
                    return _replicated_tree(trainer.compute_with_local(
                        rows, lmodel, batch, hyper), mesh2)

                def push_fn(state, local, token, delta, new_l):
                    delta = trainer.mask_delta(delta, token[2])
                    new_state = spec.push(state, token, delta)
                    dropped = jnp.sum(~token[2]).astype(jnp.float32)
                    return ((new_state, local_spec.write_all(local, new_l)),
                            dropped)

                donate = (0, 1)
            else:
                def pull_fn(state, batch):
                    keys = jax.lax.with_sharding_constraint(
                        trainer.pull_keys(batch), replicated
                    )
                    state2, rows, token = spec.pull(state, keys)
                    return state2, _replicated_tree(rows, mesh2), token

                def comp_fn(rows, batch, hyper):
                    return _replicated_tree(
                        trainer.compute(rows, batch, hyper), mesh2)

                def push_fn(state, token, delta):
                    delta = trainer.mask_delta(delta, token[2])
                    new_state = spec.push(state, token, delta)
                    dropped = jnp.sum(~token[2]).astype(jnp.float32)
                    return new_state, dropped

                donate = (0,)
        elif trainer.uses_local_table:
            def pull_fn(arr, larr):
                return _replicated_tree(
                    (spec.pull_all(arr), local_spec.pull_all(larr)), mesh2)

            def comp_fn(model, lmodel, batch, hyper):
                return _replicated_tree(
                    trainer.compute_with_local(model, lmodel, batch, hyper),
                    mesh2)

            def push_fn(arr, larr, delta, new_l):
                new_arr = spec.push_all(arr, delta)
                return ((new_arr, local_spec.write_all(larr, new_l)),
                        jnp.ravel(new_arr)[0])

            donate = (0, 1)
        elif trainer.pull_mode == "all":
            def pull_fn(arr):
                return _replicated_tree(spec.pull_all(arr), mesh2)

            def comp_fn(model, batch, hyper):
                return _replicated_tree(
                    trainer.compute(model, batch, hyper), mesh2)

            def push_fn(arr, delta):
                new_arr = spec.push_all(arr, delta)
                return new_arr, jnp.ravel(new_arr)[0]

            donate = (0,)
        else:
            keys_push = True

            def pull_fn(arr, batch):
                return _replicated_tree(
                    spec.pull(arr, trainer.pull_keys(batch)), mesh2)

            def comp_fn(model, batch, hyper):
                return _replicated_tree(
                    trainer.compute(model, batch, hyper), mesh2)

            def push_fn(arr, batch, delta):
                new_arr = spec.push(arr, trainer.pull_keys(batch), delta,
                                    via=push_route)
                return new_arr, jnp.ravel(new_arr)[0]

            donate = (0,)

        def cached(tag, build):
            return progcache.get_or_build(
                None if key is None else (key, tag), build)

        # push output pinned to the layout snapshot, exactly as the fused
        # build's out_shardings pin it (commit then re-homes nothing)
        push_out = (((tsh, lsh), None) if trainer.uses_local_table
                    else (tsh, None))
        pull_p = cached("unfused_pull",
                        lambda: jax.jit(pull_fn, donate_argnums=()))
        comp_p = cached("unfused_comp",
                        lambda: jax.jit(comp_fn, donate_argnums=()))
        push_p = cached("unfused_push",
                        lambda: jax.jit(push_fn, donate_argnums=donate,
                                        out_shardings=push_out))
        return _UnfusedStep(
            pull_p, comp_p, push_p,
            is_hash=is_hash,
            uses_local=trainer.uses_local_table,
            keys_push=keys_push,
            replicated=replicated,
        )

    def _prewarm_layout(self, new_mesh: Mesh) -> None:
        """Layout-announcement listener (TableHandle._reshard_to_owners
        announces the TARGET mesh before flipping ownership): build the
        step/epoch programs for the target layout under their progcache
        key and run ONE zero-input dispatch so XLA compiles NOW, while
        training still runs on the old layout — the post-flip rebuild then
        finds warm wrappers and the migrated epoch costs ~the move instead
        of a recompile (ref: the access-latch-only stall of
        MigrationExecutor.java:163-253). Best-effort: any failure falls
        back to the ordinary rebuild."""
        try:
            from harmony_tpu.table.hashtable import DeviceHashTable

            table = self.ctx.model_table
            is_hash = isinstance(table, DeviceHashTable)
            if not self._fused_mode():
                return  # prewarm builds fused programs only
            if self.trainer.uses_local_table:
                return  # the (model, local) pair reshards independently
            if (self.dispatch_turn is not None
                    or self._mesh_spans_processes(table.mesh)
                    or self._mesh_spans_processes(new_mesh)):
                # Multi-process / turnstiled: the prewarm would dispatch a
                # global program from ONE process outside the deterministic
                # schedule — the other processes never join its collectives
                # and the move wedges (same hazard class as _probe_comm's
                # guard). Pod reshard pre-warming needs a collective
                # protocol; fall back to the ordinary rebuild.
                return
            tsh_new = (tuple(table._make_shardings(new_mesh)) if is_hash
                       else table._make_sharding(new_mesh))
            if tsh_new == self._step_sharding:
                return  # announced layout == live layout: nothing to warm
            route = self._resolve_push_route()
            key = self._program_key(tsh_new, None, route)
            if key is None:
                return  # uncacheable trainer: a throwaway warm helps nobody
            fused = self._use_fused_epoch()
            stacked = None
            if fused:
                # EVERY worker pre-uploads its own stacked slice to the
                # target layout (pure H2D, no collectives) — the re-upload
                # is part of the relayout stall
                batches = list(self.data.epoch_batches())
                st_sh = NamedSharding(new_mesh, P(None, DATA_AXIS))
                stacked = tuple(
                    jax.device_put(np.stack([b[i] for b in batches]), st_sh)
                    for i in range(len(batches[0]))
                )
                self._prewarmed_stacked = (tsh_new, stacked)
                gkey = self._devcache_key_for_sig(
                    "stacked", progcache.sharding_signature(
                        NamedSharding(new_mesh, P(DATA_AXIS))
                    )
                )
                if gkey is not None:
                    devcache.put(gkey, stacked)
            if not self.global_init:
                return  # program warm is chief-only: progcache is shared,
                # so one worker's warm serves the whole job (N duplicate
                # zero-table epochs would tax the very devices training on)
            build_step, build_epoch = self._program_builders(
                tsh_new, None, route)
            step = progcache.get_or_build((key, "step"), build_step)
            epoch_fn = (progcache.get_or_build((key, "epoch"), build_epoch)
                        if fused else None)
            spec = table.spec
            if is_hash:
                # an all-EMPTY hash state (slot_keys == 0) is a valid
                # table; the dummy step's inserts are discarded
                arr0 = (
                    jax.device_put(
                        np.zeros(spec.keys_shape, np.int32), tsh_new[0]),
                    jax.device_put(
                        np.zeros(spec.values_shape, spec.dtype), tsh_new[1]),
                )
            else:
                arr0 = jax.device_put(
                    np.zeros(spec.storage_shape, spec.dtype), tsh_new
                )
            hyper = self._hyper()
            if fused:
                with dispatch_scope(new_mesh) as fin:
                    out = fin(epoch_fn(arr0, stacked, hyper))
            else:
                batch_sh = NamedSharding(new_mesh, P(DATA_AXIS))
                dummy = tuple(
                    jax.device_put(
                        np.zeros((self.data.batch_size, *tail), dt),
                        batch_sh)
                    for tail, dt in self.data.array_specs()
                )
                with dispatch_scope(new_mesh) as fin:
                    out = fin(step(arr0, dummy, hyper))
            hard_sync(out)  # compile fully done BEFORE the flip
        except Exception:
            return

    def _build_step(self) -> None:
        table = self.ctx.model_table
        data_ax = table.mesh.shape.get(DATA_AXIS, 1)
        if self.data.batch_size % max(data_ax, 1):
            raise ValueError(
                f"mini-batch size {self.data.batch_size} not divisible by the "
                f"mesh data axis ({data_ax}); pick num_mini_batches so that "
                "each batch splits evenly across data-parallel shards"
            )
        # ONE locked read of each table's layout, used for BOTH the cache
        # key and the compiled out_shardings (see _program_key docstring).
        tsh = table.sharding
        lsh = self.ctx.local_table.sharding if self.trainer.uses_local_table else None
        prev_key = self._program_cache_key if self._built_once else None
        # ONE route resolution per build, shared by the key and the traced
        # body (two resolutions could drift across a transient failure and
        # cache an executable under a key claiming a different lowering)
        self._push_route = self._resolve_push_route()
        self._program_cache_key = self._program_key(tsh, lsh, self._push_route)
        key = self._program_cache_key

        if isinstance(getattr(self, "_step", None), AsyncStepDriver):
            # rebuild fence: drain the in-flight window under the OLD
            # programs/layout before swapping them out (close re-raises a
            # pending comm failure rather than dropping it with the old
            # driver)
            self._step.close()
        if self._async_mode():
            # bounded-staleness async driver over the per-phase programs
            # (cached under the async-tagged key); the driver carries the
            # phase timers and the staleness telemetry
            inner = self._build_unfused(key, tsh, lsh, self._push_route)
            self._step = AsyncStepDriver(
                inner, bound=self._staleness_bound,
                model_table=table,
                local_table=(self.ctx.local_table
                             if self.trainer.uses_local_table else None),
                mesh=table.mesh, job_id=self.job_id,
                worker_id=self.ctx.worker_id)
            self._epoch_fn = None
        elif not self._fused_mode():
            # host-driven per-phase fallback: the phase programs ride the
            # program cache under the same (mode-tagged) key; the wrapper
            # object is rebuilt per build (it carries phase timers)
            self._step = self._build_unfused(key, tsh, lsh, self._push_route)
            self._epoch_fn = None
        else:
            build_step, build_epoch = self._program_builders(
                tsh, lsh, self._push_route)
            self._step = progcache.get_or_build(
                None if key is None else (key, "step"), build_step
            )
            if self._use_fused_epoch():
                self._epoch_fn = progcache.get_or_build(
                    None if key is None else (key, "epoch"), build_epoch
                )
        self._eval_fn = progcache.get_or_build(
            None if key is None else (key, "eval"),
            lambda: jax.jit(self.trainer.evaluate),
        )
        # Per-batch pull size for op accounting (ref: RemoteAccessOpStat
        # counters behind MetricReportMsg): keys-mode row count comes from
        # an eval_shape of pull_keys (no compute), all-mode pulls capacity.
        if self.trainer.pull_mode == "keys":
            sample = tuple(
                jax.ShapeDtypeStruct((self.data.batch_size, *tail), dt)
                for tail, dt in self.data.array_specs()
            )
            self._pull_rows = int(
                jax.eval_shape(self.trainer.pull_keys, sample).shape[0]
            )
        else:
            self._pull_rows = int(table.spec.config.capacity)
        self._step_sharding = tsh
        self._local_sharding = lsh
        prev_batch_sig = self._batch_sig if self._built_once else None
        # hash tables snapshot a (keys, vals) sharding pair — same mesh
        mesh_now = (tsh[0] if isinstance(tsh, tuple) else tsh).mesh
        # keep the worker's mesh view current: the probe/drain dispatch
        # scopes key their global-order decision on it, and a stale 1-device
        # mesh would skip the lock for now-multi-device programs
        self.mesh = mesh_now
        self._batch_sharding = NamedSharding(mesh_now, P(DATA_AXIS))
        self._batch_cache.clear()   # cached batches live on the old mesh
        self._stacked_cache = None
        pw = self._prewarmed_stacked
        self._prewarmed_stacked = None
        if pw is not None and pw[0] == tsh:
            # the announcement listener already uploaded the dataset to
            # this exact layout — skip the re-upload half of the stall
            self._stacked_cache = pw[1]
        self._probe_pull = None     # probe programs target the old layout
        # memoized: _devcache_key needs it per batch, and the signature
        # enumerates every mesh device
        self._batch_sig = progcache.sharding_signature(self._batch_sharding)
        cur_batch_sig = self._batch_sig
        if (self.data.dataset_key is not None
                and prev_batch_sig is not None
                and prev_batch_sig != cur_batch_sig):
            # An ACTUAL layout transition: release the global device buffers
            # THIS worker cached under the departed layout — otherwise up to
            # the cache budget of HBM stays pinned on devices the job may
            # have just released. Only the departed signature is dropped
            # (never "everything unlike mine"): another tenant's buffers
            # under a different live layout must survive, and a dropped
            # entry in concurrent use stays valid anyway (drops only forget
            # the cache reference; device buffers are immutable).
            devcache.drop(
                lambda k: k[0] == self.data.dataset_key
                and k[2] == prev_batch_sig
            )
        if (prev_key is not None and key != prev_key):
            # Same for compiled programs: the departed layout's executables
            # (out_shardings bound to possibly-released devices) can never
            # hit again under the old key.
            progcache.drop(lambda k: k[0] == prev_key)
        self._built_once = True
        # tenant cost accounting: (re)bind this job's tables for byte
        # attribution, refresh the resident-table gauge, and invalidate
        # the cached per-step FLOP figure (the new build may trace a
        # different program). Guarded: accounting never fails a build.
        self._flops_per_step = None
        try:
            from harmony_tpu.metrics.accounting import ledger

            acct = ledger()
            acct.bind_table(table.spec.table_id, self.job_id,
                            self.attempt_key)
            if self.trainer.uses_local_table:
                acct.bind_table(self.ctx.local_table.spec.table_id,
                                self.job_id, self.attempt_key)
            acct.set_resident(self.job_id, self.attempt_key, "table",
                              self._table_resident_bytes())
            if self._slo_target is not None:
                acct.set_slo_target(self.job_id, self.attempt_key,
                                    self._slo_target)
        except Exception:
            pass

    def _build_comm_probe(self) -> None:
        """Standalone PULL and PULL+PUSH(zero-delta) programs mirroring the
        step's table traffic.

        The fused step folds pull/push into one XLA program, so their time
        is unobservable from outside — and the elasticity optimizer's cost
        model degenerates without a comm/comp split (more shards always
        looks free). These probes make the split measurable: dispatching
        PULL alone times the model-axis all-gather; PULL+PUSH adds the
        delta fold's scatter/reduction; the step time minus both is comp.
        The reference fed its optimizer per-op pull/push timers
        (dolphin/core/worker/ModelAccessor.java:33-49); one probe per
        epoch is the fused-mode equivalent. Non-donating (the live table
        buffer must survive), so a probe transiently holds one extra copy
        of the table array."""
        from harmony_tpu.table.hashtable import DeviceHashTable

        spec = self.ctx.model_table.spec
        trainer = self.trainer
        if isinstance(self.ctx.model_table, DeviceHashTable):
            replicated = NamedSharding(self.ctx.model_table.mesh, P())

            def pull_fn(state, batch):
                keys = jax.lax.with_sharding_constraint(
                    trainer.pull_keys(batch), replicated
                )
                _, rows, _ = spec.pull(state, keys)
                return rows

            def pp_fn(state, batch):
                keys = jax.lax.with_sharding_constraint(
                    trainer.pull_keys(batch), replicated
                )
                new_state, rows, token = spec.pull(state, keys)
                return spec.push(new_state, token, jnp.zeros_like(rows))

        elif trainer.pull_mode == "all":

            def pull_fn(arr, batch):
                return spec.pull_all(arr)

            def pp_fn(arr, batch):
                model = spec.pull_all(arr)
                return spec.push_all(arr, jnp.zeros_like(model))

        else:
            push_via = self._push_route  # resolved by _build_step

            def pull_fn(arr, batch):
                return spec.pull(arr, trainer.pull_keys(batch))

            def pp_fn(arr, batch):
                keys = trainer.pull_keys(batch)
                rows = spec.pull(arr, keys)
                return spec.push(arr, keys, jnp.zeros_like(rows), via=push_via)

        key = self._program_cache_key
        self._probe_pull = progcache.get_or_build(
            None if key is None else (key, "probe_pull"),
            lambda: jax.jit(pull_fn),
        )
        self._probe_pp = progcache.get_or_build(
            None if key is None else (key, "probe_pp"),
            lambda: jax.jit(pp_fn),
        )

    @staticmethod
    def _mesh_spans_processes(mesh: Mesh) -> bool:
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        return mesh_spans_processes(mesh)

    def _fused_mode(self) -> bool:
        """Whether this worker's step dispatches as ONE fused program.
        The unfused fallback is host-driven (each phase round-trips
        through host memory), so a multi-process mesh — whose shards no
        single process can materialize — keeps the fused path regardless
        of the knob."""
        # async mode is host-driven per-phase BY CONSTRUCTION (the comm
        # thread dispatches push/pull standalone) — it pre-empts the
        # fused knob, and _async_mode() checks the mesh itself so there
        # is no recursion through here
        if self._async_mode():
            return False
        if self._fused_on:
            return True
        # the TABLE's mesh, not self.mesh: the decision must track the
        # live layout even between a reshard and the post-flip rebuild
        return self._mesh_spans_processes(self.ctx.model_table.mesh)

    def _async_capable(self) -> bool:
        """Whether the live (table, trainer, layout) combination can run
        the bounded-staleness async step: dense pull_mode='all' tables
        on a single-process mesh. Hash/keys-mode steps pull per-batch
        rows (the published-view pipeline has no batch when it pulls),
        and a multi-process mesh cannot materialize the host round-trip.
        Exposed to the tenant ledger so the policy engine knows the
        `async` lever exists before proposing it."""
        from harmony_tpu.table.hashtable import DeviceHashTable

        if isinstance(self.ctx.model_table, DeviceHashTable):
            return False
        if self.trainer.pull_mode != "all":
            return False
        return not self._mesh_spans_processes(self.ctx.model_table.mesh)

    def _async_mode(self) -> bool:
        """Whether this worker's step runs the async driver NOW."""
        return self._async_on and self._async_capable()

    def _probe_comm(self, batch: Tuple[np.ndarray, ...]) -> None:
        """Time the probe programs on one batch (warmup dispatch first so
        compile never lands in the measurement); stores (pull_s, push_s)
        for _emit_batch_metrics — on the SHARED table, so every worker of
        the job reads the chief's measurement instead of re-measuring the
        same table's cost (the probe blocks the table lock for several
        device round-trips; once per job per epoch is enough). A failed
        probe just skips this epoch's measurement — the previous split
        stays in effect."""
        spans = self._mesh_spans_processes(self.ctx.model_table.mesh)
        if spans and self.dispatch_turn is None and self.ctx.num_workers != 1:
            # Multi-process mesh with multiple dispatch threads and no
            # turnstile: probe programs are global collectives and a
            # divergent dispatch order would wedge the pod. (Unreachable
            # when the entity wires the turnstile; kept as a guard for
            # direct WorkerTasklet users.)
            return
        if self._probe_pull is None:
            self._build_comm_probe()

        # min-of-3 after a warmup/compile dispatch: these programs run
        # sub-millisecond on small tables and the split comes from a
        # SUBTRACTION, so single-shot jitter would routinely invert it.
        # Under multi-tenant contention each dispatch waits behind other
        # tenants' steps at the dispatch lock, so the sample count drops
        # to 1 — a noisier split beats stalling a cheap tenant for eight
        # serialized waits.
        samples = (1 if self.taskunit is not None and self.taskunit.contended()
                   else 3)

        def timed(fn, *args) -> float:
            # The global dispatch scope wraps each DISPATCH, not the whole
            # loop — on async backends the wait happens outside the lock, so
            # other tenants never stall behind a probe's round-trips.
            def once() -> float:
                t0 = time.perf_counter()
                # model-pull wire-time fault site INSIDE the timed
                # region: a "delay" rule injects deterministic comm
                # latency the probe then honestly MEASURES into the
                # split — the phase-budget acceptance's injection point
                # (the blockmove.send delay-rule precedent)
                if faults.armed():
                    faults.site("worker.pull", job=self.job_id,
                                worker=self.ctx.worker_id, probe=1)
                with dispatch_scope(self.mesh) as fin:
                    out = fin(fn(*args))
                # hard_sync, not block_until_ready: on the lazy axon
                # backend the latter is a no-op and the measured split
                # would be pure dispatch noise
                hard_sync(out)
                return time.perf_counter() - t0

            once()  # warmup/compile
            return min(once() for _ in range(samples))

        try:
            # Under the table lock: another worker's DONATING step must not
            # invalidate the state buffer mid-probe (same rule as every
            # host accessor — see DenseTable.array). The lock is held for
            # the few-ms probe dispatches, once per epoch.
            with self.ctx.model_table._lock:
                state = self.ctx.model_table._step_state
                batch_dev = self._shard_batch(batch)
                t_pull = timed(self._probe_pull, state, batch_dev)
                t_pp = timed(self._probe_pp, state, batch_dev)
        except Exception:
            if spans:
                # A one-sided probe failure on a multi-process mesh has
                # already desynchronized the pod's dispatch order (this
                # process dispatched fewer global programs than its
                # peers). Failing the job fast beats wedging the pod in a
                # collective that can never complete.
                raise
            # a probe failure (layout race, donated buffer, transient
            # backend error) must never kill training — skip this epoch's
            # measurement and rebuild the programs next time
            self._probe_pull = None
            return
        self._comm_probe_times = (t_pull, max(t_pp - t_pull, 0.0))
        # publish for sibling workers sharing this table (read at emit
        # time) through the table's typed accessor — a lock-fenced
        # publication, not a private-attr poke
        self.ctx.model_table.set_comm_split(self._comm_probe_times)

    def _use_fused_epoch(self) -> bool:
        """Whole-epoch compilation is only correct with no between-batch host
        decisions: no SSP gate, no TaskUnit scheduling, stable batches.
        Under a TaskUnit scheduler the per-batch path is kept so concurrent
        tenants interleave at BATCH granularity (one fused epoch would hand
        one tenant the device for a whole epoch per grant)."""
        return (
            self.batch_barrier is None
            and self.taskunit is None
            and not self.data.is_shuffling
            and self._fused_mode()  # host round-trips cannot lax.scan
        )

    # Max fused epochs per drain. Each drained window costs one full
    # host<->device round trip (~40-90ms over a remote-attach tunnel); on
    # small PS jobs those round trips, not compute, dominate the epoch
    # loop. Bounded so donated-buffer chains and metric latency stay short.
    EPOCH_WINDOW = 8

    def _epoch_window_len(self, epoch: int, num_epochs: int) -> int:
        """How many consecutive epochs may dispatch before the next drain.

        >1 only when nothing on the host needs to OBSERVE state between
        epochs: no SSP barrier (its stop decisions are per batch), a
        windowable trainer hook (see Trainer.epoch_hook_windowable), and
        an epoch callback that is either absent or declared deferrable
        (metrics-only). Works over both the fused-epoch and the
        async-batched dispatch paths (the latter keeps per-batch TaskUnit
        admission, so multi-tenant interleaving is unchanged). The window
        never crosses a comm-probe epoch — the probe measures the live
        table between dispatches."""
        if self.batch_barrier is not None:
            return 1
        if not self._fused_mode():
            # unfused steps block on host round-trips per phase: a window
            # would only batch the metric drain of an already-synchronous
            # loop — keep the honest per-epoch cadence
            return 1
        if self.pod_contended is not None and self.pod_contended():
            # Cross-job pod tenancy: a multi-epoch window is one dispatch
            # UNIT, and co-tenants wait out whole units — contended jobs
            # interleave at single-epoch granularity instead. The flag is
            # read at the last completed unit's exit (deterministic
            # pod-wide), so every process shrinks at the same epoch.
            return 1
        # un-overridden hooks are no-ops (windowable by construction);
        # overriders must OPT IN at the class that defines the hook
        if not Trainer._epoch_hook_windowable(self.trainer):
            return 1
        if self.epoch_callback is not None and not self.defer_epoch_callback:
            return 1
        w = min(self.EPOCH_WINDOW, num_epochs - epoch)
        if self.pending_plan_epoch is not None:
            due = self.pending_plan_epoch()
            if due is not None and due >= epoch:
                w = min(w, due - epoch + 1)  # window ends AT the plan epoch
        if self.comm_probe_every and self.global_init:
            if self._probe_pull is None:
                # a probe (re)build is due at this epoch boundary — keep
                # per-epoch until it has run (first epoch / after reshard)
                w = min(w, 1)
            else:
                until = self._next_probe - (epoch - self.starting_epoch)
                if until > 0:
                    w = min(w, until)
        return max(1, w)

    def _maybe_rebuild(self) -> None:
        """Live re-sharding: if EITHER table's layout changed since compile
        (plan-driven migration), rebuild so out_shardings/donation target the
        new mesh instead of pinning results to released devices."""
        if self.ctx.model_table.sharding != self._step_sharding:
            self._build_step()
        elif (
            self.trainer.uses_local_table
            and self.ctx.local_table.sharding != self._local_sharding
        ):
            self._build_step()

    def _shard_batch(self, batch: Tuple[np.ndarray, ...]):
        return tuple(jax.device_put(a, self._batch_sharding) for a in batch)

    def _host_batch(self, batch_idx: int, batch):
        """The host arrays for ``batch_idx`` — ``batch`` when the caller
        carried them, else re-materialized from the provider (only reached
        on stable-batch paths: a devcache-bypass epoch whose cache a live
        reshard just cleared)."""
        if batch is not None:
            return batch
        return self.data.batch_at(batch_idx)

    def _prefetch_usable(self) -> bool:
        """Background staging is safe only where this worker's device_puts
        may interleave freely with dispatches: pod-lockstep turnstiles
        need every multi-device operation inside an admission turn, and on
        multi-process meshes a device_put that replicates across processes
        is itself collective-backed — both would wedge under a producer
        thread. The TaskUnit fair queue is fine (staging rides it as NET
        units when single-worker — see _epoch_batch_stream)."""
        return (
            self._prefetch_on
            and self.dispatch_turn is None
            and not self._staging_unsafe  # announced spanning target
            and not self._mesh_spans_processes(self.mesh)
        )

    def _devcache_epoch_ready(self) -> bool:
        """True when EVERY batch of the (stable) epoch already has a
        device-resident copy — the epoch then bypasses host assembly and
        staging entirely (the devcache-hit fast path)."""
        if not self.cache_device_batches:
            return False
        nb = self.data.num_mini_batches
        if len(self._batch_cache) == nb:
            return True
        return all(
            i in self._batch_cache or devcache.contains(self._devcache_key(i))
            for i in range(nb)
        )

    def _epoch_batch_stream(self, epoch: int):
        """One epoch's input as (batch_idx, host_batch | None, StagedBatch
        | None) triples — the three input regimes behind one iterator:

          * devcache-hit epoch: every batch is device-resident already;
            host assembly is bypassed entirely (host_batch is None);
          * prefetched epoch: a PrefetchPipeline producer assembles and
            stages batches ahead of the compute loop;
          * synchronous fallback (config off / pod lockstep /
            multi-process mesh): the pre-pipeline behavior, unchanged.

        Callers MUST close() the returned generator (the dispatch loop's
        finally does) so an early stop tears the producer down."""
        # ONE ready evaluation for both the handoff decision and the
        # branch below: a sibling worker devcache.put-ing the last batch
        # between two evaluations could flip it and strand the handoff
        # unclosed (leaked producer thread + staged device buffers)
        ready = self._devcache_epoch_ready()
        handoff, self._next_pipeline = self._next_pipeline, None
        if handoff is not None and (handoff[0] != epoch or ready):
            # wrong-epoch (defensive; epochs stream in order) or the cache
            # filled (stable batches only — no RNG was drawn): tear the
            # pre-spawn down before any fallback path
            handoff[1].close()
            handoff = None
        if ready:
            for i in range(self.data.num_mini_batches):
                yield i, None, None
            return
        if not self._prefetch_usable():
            if handoff is not None:
                # usability flipped AFTER the spawn (reshard onto a
                # spanning mesh): the producer already drew this epoch's
                # shuffle, so abandoning it would double-advance the RNG
                # and break seeded parity — consume it in host-only mode
                # (no background device_puts) instead
                handoff[1].stop_staging()
            else:
                # synchronous fallback: the feed (when present) must
                # still be the source — its epoch replay never advanced
                # the provider's sequential RNG, so epoch_batches() here
                # would replay epoch 0's draw
                src = (self._input_feed.epoch_iter(epoch)
                       if self._input_feed is not None
                       else self.data.epoch_batches())
                for i, b in enumerate(src):
                    yield i, b, None
                return
        if handoff is not None:
            # pre-spawned during the previous epoch's drain: batch 0 is
            # (usually) already staged — no epoch-start input stall
            pipeline = handoff[1]
        else:
            pipeline = self._make_pipeline(epoch)
        self._active_pipeline = pipeline
        if self._staging_unsafe:
            # an announcement may have raced pipeline construction (the
            # listener demotes only pipelines it can SEE); recheck after
            # the assignment so one side always lands — idempotent
            pipeline.stop_staging()
        self._prefetch_hits = 0
        self._prefetch_misses = 0
        try:
            for staged in pipeline:
                yield staged.index, staged.host, staged
        finally:
            self._active_pipeline = None
            pipeline.close()
            self._emit_prefetch_metrics(epoch, pipeline)

    def _make_pipeline(self, epoch: int) -> PrefetchPipeline:
        net_scope = None
        if self.taskunit is not None and self.ctx.num_workers == 1:
            # staging transfers ride the fair queue as NET units (the
            # reference's PULL/PUSH resource class) with an interruptible
            # admission wait (teardown must not hang on a grant that can
            # no longer arrive) — but only for single-worker jobs:
            # TaskUnit quorum matches per-worker seq streams, and
            # producer-timed units would misalign them across a
            # multi-worker job's executors
            net_scope = lambda abort: self.taskunit.scope(  # noqa: E731
                "NET", abort=abort)
        skip_staged = None
        if self.cache_device_batches:
            # partial-cache epochs (one LRU-evicted batch) re-stage only
            # what is actually missing; resident batches flow host-only
            skip_staged = lambda i: (  # noqa: E731
                i in self._batch_cache
                or devcache.contains(self._devcache_key(i))
            )
        epoch_source = None
        if self._input_feed is not None:
            feed = self._input_feed
            # bound per pipeline: each pipeline owns ONE epoch's stream
            epoch_source = lambda: feed.epoch_iter(epoch)  # noqa: E731
        return PrefetchPipeline(
            self.data,
            lambda: self._batch_sharding,
            self._inflight_cap,
            epoch=epoch,
            job_id=self.job_id,
            net_scope=net_scope,
            skip_stage_fn=skip_staged,
            epoch_source=epoch_source,
        )

    def _spawn_next_pipeline(self, next_epoch: int) -> None:
        """Cross-epoch overlap: spawned right BEFORE this epoch's metric
        drain (its blocking device round-trips are the one host-idle
        window of the batched loop), so the next epoch's gather and
        staging run during the drain and batch 0 is ready when the next
        stream opens. Only called after the current epoch's stream fully
        drained, so the provider's per-epoch RNG draws stay in epoch
        order — seeded shuffles match the synchronous path exactly."""
        if self._next_pipeline is not None:
            return
        if next_epoch >= self.ctx.params.num_epochs:
            return
        if not self._prefetch_usable() or self._devcache_epoch_ready():
            return
        pipeline = self._make_pipeline(next_epoch)
        self._next_pipeline = (next_epoch, pipeline)
        if self._staging_unsafe:
            # announcement raced the spawn (see _epoch_batch_stream)
            pipeline.stop_staging()

    def _close_next_pipeline(self) -> None:
        if self._next_pipeline is not None:
            self._next_pipeline[1].close()
            self._next_pipeline = None

    def _emit_prefetch_metrics(self, epoch: int, pipeline: PrefetchPipeline) -> None:
        s = pipeline.stats()
        svc = fb = 0
        if self._input_feed is not None:
            # EXACT per-epoch attribution from the feed (a cumulative
            # delta would misattribute when the pre-spawned next-epoch
            # pump lands batches before this epoch's emit)
            es = self._input_feed.epoch_stats(epoch)
            svc = es["service"]
            fb = es["fallbacks"]
        self.collector.add(
            InputPipelineMetrics(
                job_id=self.job_id,
                worker_id=self.ctx.worker_id,
                epoch_idx=epoch,
                staged_batches=s["staged"],
                prefetch_hits=self._prefetch_hits,
                prefetch_misses=self._prefetch_misses,
                max_depth=s["max_depth"],
                produce_sec=s["produce_sec"],
                stage_sec=s["stage_sec"],
                producer_idle_sec=s["producer_idle_sec"],
                consumer_stall_sec=s["consumer_stall_sec"],
                dropped_batches=s["dropped_batches"],
                service_batches=svc,
                service_fallbacks=fb,
            )
        )
        # input_wait phase (metrics/phases.py): staged per epoch here —
        # the stream closes inside the dispatch loop, before the epoch
        # wall is known at _finish_epoch, where the budget feeds
        self._phase_input_wait[epoch] = float(s["consumer_stall_sec"])
        try:  # tenant ledger: input-wait seconds feed the wait fraction
            from harmony_tpu.metrics.accounting import ledger

            ledger().record_input_wait(self.job_id, self.attempt_key,
                                       s["consumer_stall_sec"])
        except Exception:
            pass

    def _on_layout_announcement(self, new_mesh: Mesh) -> None:
        """Reshard announcement listener: staged input batches target the
        departing layout — drop their device copies (the consumer
        re-places the retained host arrays on the live mesh), THEN prewarm
        the target layout's programs. A target mesh that SPANS processes
        makes background device_puts collective-backed, so there the
        producers are demoted to host-only assembly (they keep the epoch
        RNG draw; the consumer places on the live mesh) instead of merely
        invalidated."""
        unsafe = self._mesh_spans_processes(new_mesh)
        # sticky until a later announcement says otherwise: the worker's
        # own mesh view (self.mesh) only updates at the post-flip rebuild,
        # so _prefetch_usable would otherwise green-light one more staging
        # producer in the announcement->flip window
        self._staging_unsafe = unsafe
        # snapshot both attributes: the training thread concurrently
        # hands off / nulls them (this listener runs on the master thread)
        nxt = self._next_pipeline
        for pipeline in (
            self._active_pipeline,
            nxt[1] if nxt is not None else None,
        ):
            if pipeline is None:
                continue
            if unsafe:
                pipeline.stop_staging()
            else:
                pipeline.invalidate()
        self._prewarm_layout(new_mesh)

    def _devcache_key_for_sig(self, tag, sig) -> "tuple | None":
        """devcache key under an EXPLICIT layout signature (the prewarm
        path registers uploads for a layout that is not live yet)."""
        if self.data.dataset_key is None:
            return None
        return (self.data.dataset_key, tag, sig)

    def _devcache_key(self, tag) -> "tuple | None":
        """Key into the process-level device data cache (data/devcache) —
        None unless the provider carries a data-source identity."""
        return self._devcache_key_for_sig(tag, self._batch_sig)

    def _cached_batch(self, batch_idx: int, batch):
        """Device copy of one batch. The global cache (when the dataset has
        an identity) lets resubmitted jobs reuse device buffers; the
        per-worker cache is ALWAYS kept as well, so a dataset that blows the
        global byte budget (LRU thrash, 0% hit rate) still uploads at most
        once per worker — never worse than the cache-free behavior."""
        batch_dev = self._batch_cache.get(batch_idx)
        if batch_dev is not None:
            return batch_dev
        gkey = self._devcache_key(batch_idx)
        batch_dev = devcache.get(gkey) if gkey is not None else None
        if batch_dev is None:
            batch_dev = self._shard_batch(self._host_batch(batch_idx, batch))
            if gkey is not None:
                devcache.put(gkey, batch_dev)
        self._batch_cache[batch_idx] = batch_dev
        return batch_dev

    # Bounded retries when a live reshard lands BETWEEN the rebuild check
    # and the dispatch (a step compiled for the old layout then receives the
    # new-layout array — XLA raises a device-mismatch at dispatch time, the
    # step does not execute). Reshards are rare; one retry usually wins.
    MAX_RESHARD_RETRIES = 4

    @staticmethod
    def _is_layout_race(e: ValueError) -> bool:
        return "incompatible devices" in str(e)

    def _dispatch_batch(self, batch_idx: int, batch, hyper,
                        staged: "Optional[StagedBatch]" = None):
        """Rebuild-check + batch placement + dispatch, retried across
        concurrent reshards (the batch cache re-populates on the new mesh
        after a rebuild clears it). ``staged`` is a prefetched device copy;
        it is used only while its sharding still matches the live step's
        (a reshard invalidates it and the host copy is re-placed)."""
        # step-boundary fault site (armed()-guarded: disarmed cost is one
        # global read — no ctx dict, no site dispatch). A "crash" rule
        # here kills THIS process mid-epoch like a SIGKILL'd follower —
        # the deterministic trigger the pod recovery tests arm via the
        # env-serialized plan (match on proc to pick the victim).
        if faults.armed():
            faults.site(
                "worker.step", job=self.job_id, worker=self.ctx.worker_id,
                batch=batch_idx, proc=jax.process_index(),
            )
        for _ in range(self.MAX_RESHARD_RETRIES):
            self._maybe_rebuild()
            # host-dispatch phase (metrics/phases.py): the host seconds
            # between batch-ready and the device dispatch call —
            # placement, cache lookups, staging takes. Timed so the
            # budget can subtract it from the smeared step wall; the
            # fault site INSIDE the region lets a "delay" rule inject a
            # deterministic host stall the budget then measures (the
            # dispatch-bound acceptance scenario).
            t_place = time.perf_counter()
            if faults.armed():
                faults.site("worker.dispatch", job=self.job_id,
                            worker=self.ctx.worker_id, batch=batch_idx)
            batch_dev = staged.take(self._batch_sharding) if staged is not None else None
            if batch_dev is not None:
                self._prefetch_hits += 1
                if self.cache_device_batches and batch_idx not in self._batch_cache:
                    # seed the caches with the prefetched copy so later
                    # epochs (and resubmissions) bypass host work entirely
                    self._batch_cache[batch_idx] = batch_dev
                    gkey = self._devcache_key(batch_idx)
                    if gkey is not None:
                        devcache.put(gkey, batch_dev)
            elif self.cache_device_batches:
                if staged is not None:
                    self._prefetch_misses += 1
                batch_dev = self._cached_batch(batch_idx, batch)
            else:
                if staged is not None:
                    self._prefetch_misses += 1
                batch_dev = self._shard_batch(self._host_batch(batch_idx, batch))
            self._phase_dispatch_acc += time.perf_counter() - t_place
            # model-pull wire-time site on the step path proper (the
            # probe carries its twin): a "delay" rule makes each step
            # pay the injected comm latency the probe measured, so the
            # budget's pull_comm attribution matches the wall it splits.
            # The async driver fires this site on its COMM thread instead
            # (inside the overlapped window — firing it here too would
            # double-bill the injected latency onto the compute thread).
            if faults.armed() and not isinstance(self._step, AsyncStepDriver):
                faults.site("worker.pull", job=self.job_id,
                            worker=self.ctx.worker_id, batch=batch_idx)
            try:
                return self._dispatch_step(self._step, batch_dev, hyper)
            except ValueError as e:
                if not self._is_layout_race(e):
                    raise
                # FORCE a rebuild: the race proves something layout-derived
                # is stale even if the cheap sharding compare above missed
                # it (every cache repopulates on the current mesh). The
                # staged copy targets the departed layout — drop it.
                staged = None
                self._build_step()
        raise RuntimeError(
            f"table resharded {self.MAX_RESHARD_RETRIES}x during one batch "
            "dispatch; reconfiguration is outpacing training"
        )

    def _hyper(self) -> Dict[str, jnp.ndarray]:
        return {k: jnp.asarray(v) for k, v in self.trainer.hyperparams().items()}

    def _dispatch_step(self, fn, batch_like, hyper=None):
        """Route the dispatch through the owning table lock(s)."""
        from harmony_tpu.table.table import DenseTable

        if hyper is None:
            hyper = self._hyper()
        if isinstance(fn, AsyncStepDriver):
            # the driver routes its own table-lock dispatches: COMP here
            # on the training thread (against the published view — no
            # table lock needed), PUSH+PULL on its comm thread through
            # apply_step
            return fn.submit(batch_like, hyper)
        if self.trainer.uses_local_table:
            return DenseTable.apply_step_multi(
                [self.ctx.model_table, self.ctx.local_table],
                fn,
                batch_like,
                hyper,
            )
        return self.ctx.model_table.apply_step(fn, batch_like, hyper)

    # -- the loop --------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        """One span covers the worker's whole run — re-parented onto the
        dispatch/submit trace when the entity handed a wire context down
        (the job's epochs/steps/checkpoints/moves then share the
        submission's trace_id end to end), a fresh root otherwise."""
        with trace_span(
            "dolphin.worker",
            parent=SpanContext.from_wire(self.trace_parent),
            job_id=self.job_id,
            worker_id=self.ctx.worker_id,
            attempt=self.attempt_key,
        ):
            return self._run_inner()

    def _run_inner(self) -> Dict[str, Any]:
        ctx, params = self.ctx, self.ctx.params
        # Global init writes shared tables (multi-device programs): under
        # pod tenancy that region holds a dispatch turn/unit like any
        # batch (siblings are parked at the init barrier, but OTHER jobs'
        # units must not interleave mid-init). Turn BALANCE: the cyclic
        # turnstile admits workers in strict rotation, so chief-only
        # turns would skew the alternation and walk the SSP gate past its
        # slack INSIDE a turn (deadlock) — every worker takes the turn,
        # no-op for non-chiefs.
        if self.global_init:
            # also a TaskUnit under tenancy: un-gated init dispatches
            # collide FIFO at the raw dispatch lock behind peers' units —
            # both delaying this job's start and jittering the peers
            with self._turn(), self._taskunit_scope("CPU"):
                self.trainer.init_global_settings(ctx)
        elif self._balanced_turns() or self.taskunit is not None:
            # siblings announce the SAME init unit (empty region): the
            # TaskUnit quorum needs every worker to wait on each (seq,
            # kind), and the cyclic turnstile needs matching turn counts —
            # a chief-only unit would misalign both for the whole job
            with self._turn(), self._taskunit_scope("CPU"):
                pass
        if self.post_init_barrier is not None:
            self.post_init_barrier()
        self.trainer.on_training_start(ctx, self.starting_epoch)
        # subscribe to reshard announcements: staged input batches drop
        # their device copies and the target layout's programs compile
        # WHILE training still runs on the old one (_on_layout_announcement)
        add_listener = getattr(ctx.model_table, "add_layout_listener", None)
        if add_listener is not None:
            add_listener(self._on_layout_announcement)
        try:
            return self._run_epoch_loop(params)
        finally:
            # a pre-spawned next-epoch producer must not outlive the run
            # (early stop / exception): join it before reporting back
            self._close_next_pipeline()
            # async comm thread likewise: on the happy path the last
            # epoch's fence already drained it, so this is teardown; on
            # an exception path it is best-effort and never raises
            if isinstance(getattr(self, "_step", None), AsyncStepDriver):
                self._step.shutdown()
            remove = getattr(ctx.model_table, "remove_layout_listener", None)
            if remove is not None:
                remove(self._on_layout_announcement)

    def _run_epoch_loop(self, params) -> Dict[str, Any]:
        ctx = self.ctx
        self._build_step()
        stop = False
        global_batch_idx = 0
        epoch_losses: List[float] = []

        epoch = self.starting_epoch
        while epoch < params.num_epochs and not stop:
            # chief-only (the split is a property of the shared table, not
            # the worker; siblings read the published value). Probe batch
            # is a plain prefix slice — the provider's epoch_batches()
            # would consume a shuffle from its RNG and change seeded batch
            # order relative to a probe-free run.
            since = epoch - self.starting_epoch
            if self.comm_probe_every and self.global_init and (
                self._fused_mode()  # unfused measures phases directly
            ) and (
                self._probe_pull is None or since >= self._next_probe
            ):
                self._next_probe = since + 8 * self.comm_probe_every
                first = self.data.first_rows(self.data.batch_size)
                if first and len(first[0]):
                    if (self.dispatch_turn is not None
                            and not self._use_fused_epoch()):
                        # turnstiled/batched: defer into the first batch
                        # turn so the probe's dispatches happen inside
                        # this worker's admission slot (a separate CYCLIC
                        # turn would skew the turnstile unboundedly)
                        self._pending_probe = first
                    else:
                        # fused path (pod units are request/grant, not a
                        # cycle — an extra unit is harmless) or no turns;
                        # a TaskUnit under tenancy for the same raw-lock
                        # reason as global init — but ONLY single-worker
                        # jobs: the probe is chief-only, and a chief-only
                        # unit would misalign the multi-worker quorum's
                        # per-worker seq streams
                        scope = (self._taskunit_scope("CPU")
                                 if self.ctx.num_workers == 1
                                 else contextlib.nullcontext())
                        with trace_span("dolphin.comm_probe",
                                        job_id=self.job_id, epoch=epoch):
                            with self._turn(), scope:
                                self._probe_comm(first)
            window = self._epoch_window_len(epoch, params.num_epochs)
            if window > 1:
                # Multi-epoch window: dispatches chain on the table state
                # with trainer hooks run between them (declared windowable
                # = epoch-indexed only), ONE drain at the end, then the
                # per-epoch host bookkeeping replays in order.
                # sampled continuous device capture (chief-only: the
                # profiler is process-wide; N workers double-starting
                # would fight over one session)
                with maybe_profile_epoch(
                    epoch, self.job_id, span=window,
                    enabled=self.global_init,
                ), trace_span(
                    "dolphin.epoch_window",
                    job_id=self.job_id,
                    worker_id=self.ctx.worker_id,
                    epoch=epoch,
                    epochs=window,
                    fused=self._use_fused_epoch(),
                ):
                    if self._use_fused_epoch():
                        results, per_epoch_sec = self._run_fused_epochs(
                            epoch, window
                        )
                        global_batch_idx += (
                            window * self.data.num_mini_batches
                        )
                    else:
                        results, global_batch_idx, per_epoch_sec = (
                            self._run_batched_epochs_window(
                                epoch, window, global_batch_idx
                            )
                        )
                for j, (epoch_examples, last_metrics, nb) in enumerate(results):
                    # account THIS epoch's ops just before its callback
                    # replays, so the callback's ServerMetrics delta covers
                    # exactly one epoch
                    self._account_ops(nb)
                    self._finish_epoch(
                        epoch + j,
                        time.perf_counter() - per_epoch_sec,
                        epoch_examples,
                        last_metrics,
                        epoch_losses,
                        # all but the window's LAST hook ran between
                        # dispatches; the last runs here, post-drain, as in
                        # the unfused loop
                        call_trainer_hook=(j == len(results) - 1),
                    )
                epoch += window
                continue
            epoch_t0 = time.perf_counter()
            with maybe_profile_epoch(
                epoch, self.job_id, enabled=self.global_init,
            ), trace_span(
                "dolphin.epoch",
                job_id=self.job_id,
                worker_id=self.ctx.worker_id,
                epoch=epoch,
                fused=self._use_fused_epoch(),
            ) as span:
                if self._use_fused_epoch():
                    results, _ = self._run_fused_epochs(epoch, 1)
                    epoch_examples, last_metrics, nb1 = results[0]
                    self._account_ops(nb1)
                    global_batch_idx += self.data.num_mini_batches
                else:
                    epoch_examples, last_metrics, global_batch_idx, stop = (
                        self._run_batched_epoch(epoch, global_batch_idx)
                    )
                if epoch_examples == 0 and stop and span is not None:
                    # stopped before any batch: "not an epoch at all" below,
                    # so the span must not inflate per-epoch aggregates
                    span.discard()
            if epoch_examples == 0 and stop:
                break  # stopped before any batch: not an epoch at all
            self._finish_epoch(epoch, epoch_t0, epoch_examples, last_metrics, epoch_losses)
            epoch += 1
        self.trainer.cleanup(ctx)
        return {
            "job_id": self.job_id,
            # (starting_epoch, epochs_run) is the exactly-once evidence
            # the elastic tests stitch across recovery attempts: each
            # attempt's half-open epoch range [starting_epoch,
            # starting_epoch + epochs_run) must tile [0, num_epochs)
            "starting_epoch": self.starting_epoch,
            "epochs_run": len(epoch_losses),
            "losses": epoch_losses,
            "stopped_early": stop,
        }

    # Bound on steps enqueued without a device sync (keeps the dispatch
    # queue and donated-buffer chain short on long epochs).
    MAX_INFLIGHT = 32
    # Under multi-tenant contention the deep window becomes the UNFAIRNESS:
    # another tenant's next unit waits behind this job's whole enqueued
    # backlog (measured 15x slowdown for the cheapest tenant, FAIRNESS_r02)
    # — so contended jobs keep the device queue shallow.
    CONTENDED_INFLIGHT = 2

    def _inflight_cap(self) -> int:
        if self.taskunit is not None and self.taskunit.contended():
            return self.CONTENDED_INFLIGHT
        return self.MAX_INFLIGHT

    def _run_batched_epoch(
        self, epoch: int, global_batch_idx: int
    ) -> Tuple[int, Dict[str, float], int, bool]:
        """Per-batch dispatch with SYNC gate + TaskUnit announcement.

        Dispatch is ASYNC: steps enqueue without blocking, metrics stay on
        device, and ONE stacked transfer per metric key at epoch end fetches
        them all — on a remote-attached chip every per-batch scalar read
        costs a full network round-trip (~100ms measured), so per-step
        blocking dominated wall time. Blocking on the step's own outputs
        (never a table snapshot a donating step could invalidate) is
        preserved; it just happens once per epoch / in-flight window.

        TaskUnit semantics under async dispatch: the COMP scope gates
        ADMISSION, not occupancy. The device executes one XLA program at a
        time, so the globally-coordinated grant order becomes the device
        queue order — which is the interleaving the reference's occupancy
        slots produced on CPU executors (and, multi-host, identical
        enqueue order across hosts is what keeps collectives
        deadlock-free). Holding the slot through device execution would
        add a full tunnel round-trip per batch without changing the
        device-side serialization.
        """
        pending, batch_sizes, epoch_examples, global_batch_idx, stop, work_t = (
            self._dispatch_epoch_batches(epoch, global_batch_idx)
        )
        if isinstance(self._step, AsyncStepDriver):
            # epoch fence: every submitted delta applies (in submission
            # order) before anything host-side observes the table —
            # metric drains, snapshots, trainer epoch hooks, elastic
            # fences. This is what keeps the (seed, epoch,
            # step-apply-order) replay contract exact under async.
            t0 = time.perf_counter()
            self._step.drain()
            work_t += time.perf_counter() - t0
        dispatch_sec = self._take_dispatch_sec()
        if not stop:
            # next epoch's host assembly runs while the drain below blocks
            # (under TaskUnit contention its STAGING still queues behind
            # the drain's NET unit — per-kind metering admits one NET unit
            # at a time across tenants, by design; the gather/shuffle work
            # overlaps regardless)
            self._spawn_next_pipeline(epoch + 1)
        last_metrics: Dict[str, float] = {}
        if pending:
            with trace_span("dolphin.metric_drain", job_id=self.job_id,
                            epoch=epoch, batches=len(pending)):
                # the drain's stack programs are multi-device dispatches:
                # under pod lockstep they take a turn like any batch, and
                # under TaskUnit tenancy they are a NET unit (a transfer
                # phase, like the reference's PULL/PUSH typing) so they
                # ride the fair queue instead of colliding FIFO at the
                # raw dispatch lock behind peers' compute units. The
                # timer starts INSIDE — admission wait is scheduling, not
                # work, and must not inflate the per-batch times feeding
                # the optimizer's cost model.
                with self._turn(), self._taskunit_scope("NET"):
                    t0 = time.perf_counter()
                    host = self._drain_pending(pending)
            work_t += time.perf_counter() - t0
            # Async dispatch makes true per-batch device time unobservable
            # without per-step syncs; smear the epoch's work time (barrier
            # waits excluded) evenly — averages feeding the optimizer stay
            # right, per-batch variance is deliberately given up.
            last_metrics = self._emit_batch_metrics(
                epoch, host, batch_sizes, work_t / len(pending),
                dispatch_sec=dispatch_sec,
            )
            self._account_ops(len(pending))
        return epoch_examples, last_metrics, global_batch_idx, stop

    # Target span of one admitted TaskUnit under contention: a cheap job
    # pays ~one residual big-unit wait per OWN unit (non-preemptive slot),
    # so per-batch units make its slowdown scale with the PEERS' batch
    # time. Grouping consecutive batches until a unit spans ~this many
    # seconds normalizes unit granularity in TIME across tenants. 60ms:
    # the residual a cheap tenant eats per grant scales with THIS number
    # (FAIRNESS max-slowdown was the cheapest job at 0.1), while grants
    # themselves are in-process condition-variable ops — near free.
    UNIT_SPAN_TARGET = 0.06

    def _units_per_scope(self) -> int:
        if self.batch_barrier is not None:
            return 1  # the SSP gate is per batch; never hold a slot on it
        if self.taskunit is not None:
            if not self.taskunit.contended():
                return 1
            c = self._own_batch_cost
            if c is None:
                return 1
            # A tenant pays ~one residual PEER-unit wait per own unit
            # (non-preemptive slot), so the dominant slowdown term for a
            # cheap job is its UNIT COUNT, not its unit size: stretch the
            # span target toward the largest peer unit (bounded — never
            # hold the slot longer than half a second) so a cheap job
            # crosses the schedule few times instead of once per batch.
            target = self.UNIT_SPAN_TARGET
            peer = self.taskunit.peer_unit_cost()
            if peer:
                target = max(target, min(peer, 0.5))
            return max(1, min(8, int(target / max(c, 1e-6))))
        if self.pod_contended is not None and self.dispatch_turn is not None:
            # Pod units on the batched path: group a FIXED batch count per
            # unit so an uncontended job does not pay a leader round trip
            # per mini-batch. Fixed, not UNIT_SPAN_TARGET-measured — the
            # group size must be identical on every process (a local
            # timing would diverge the unit sequence and wedge the pod);
            # the contended flag is deterministic (read at unit exit).
            return 1 if self.pod_contended() else 8
        return 1

    def _dispatch_epoch_batches(self, epoch: int, global_batch_idx: int):
        """The per-batch dispatch loop of one epoch — async, TaskUnit
        admission per batch group (see _units_per_scope), NO drain.
        Returns (pending device metrics, batch_sizes, examples,
        global_batch_idx, stop, dispatch_seconds)."""
        epoch_examples = 0
        stop = False
        pending: List[Dict[str, jnp.ndarray]] = []
        batch_sizes: List[int] = []
        hyper = self._hyper()
        work_t = 0.0  # dispatch time, EXCLUDING admission/barrier waits
        it = self._epoch_batch_stream(epoch)
        try:
            nxt = next(it, None)
            while nxt is not None and not stop:
                with self._turn():
                    if self._pending_probe is not None:
                        # turnstiled pods probe inside the chief's first batch
                        # turn (a separate probe turn would skew the cycle by
                        # one turn per probe epoch, unboundedly across epochs)
                        first, self._pending_probe = self._pending_probe, None
                        with trace_span("dolphin.comm_probe",
                                        job_id=self.job_id, epoch=epoch):
                            self._probe_comm(first)
                    if self.batch_barrier is not None:  # SYNC TaskUnit
                        stop = self.batch_barrier(global_batch_idx)
                        if stop:
                            break
                    group = self._units_per_scope()
                    with self._taskunit_scope("COMP"):
                        # timer starts AFTER admission: the grant wait is
                        # scheduling, not work — counting it would both skew
                        # the optimizer's comm/comp split and feed an
                        # inflated unit cost back into the fair-queue deficit
                        # (a starved cheap job would look expensive and be
                        # starved harder)
                        t_scope = time.perf_counter()
                        done = 0
                        while nxt is not None and done < group:
                            batch_idx, batch, staged = nxt
                            t0 = time.perf_counter()
                            metrics = self._dispatch_batch(
                                batch_idx, batch, hyper, staged
                            )
                            pending.append(metrics)
                            cap = self._inflight_cap()
                            if len(pending) >= cap:
                                # Sliding window: block on the OLDEST
                                # outstanding step so the device queue stays
                                # full. hard_sync so a lazy backend actually
                                # applies backpressure.
                                hard_sync(pending[len(pending) - cap])
                            # dt spans dispatch AND the backpressure sync: on
                            # async backends the sync absorbs real device time
                            # that would otherwise land in neither work_t nor
                            # the drain (those steps are complete by then)
                            dt = time.perf_counter() - t0
                            # own per-batch EWMA sizes future groups (None =
                            # unseeded; a measured 0.0 is a real sample)
                            self._own_batch_cost = (
                                dt if self._own_batch_cost is None
                                else 0.5 * self._own_batch_cost + 0.5 * dt
                            )
                            work_t += dt
                            # bypass epochs carry no host arrays; the
                            # provider's equal split fixes the batch size
                            n_ex = (batch[0].shape[0] if batch is not None
                                    else self.data.batch_size)
                            batch_sizes.append(n_ex)
                            epoch_examples += n_ex
                            global_batch_idx += 1
                            done += 1
                            if done < group:
                                nxt = next(it, None)
                            else:
                                nxt = None  # refetched below
                        if self.taskunit is not None:
                            # live per-UNIT cost for the weighted-fair queue:
                            # the drain-time report (authoritative on async
                            # backends) can be a whole multi-epoch window
                            # away, and a blind WFQ degenerates to 1:1
                            # pacing. Under the metered global slot the
                            # in-scope elapsed is ~this unit's own execution
                            # (blocking backends) or its enqueue cost
                            # (async) — either way job-relative.
                            self.taskunit.report_unit_cost(
                                time.perf_counter() - t_scope
                            )
                if not stop:
                    nxt = next(it, None)
        finally:
            # an early stop (SSP gate) or a raising dispatch must tear the
            # prefetch producer down NOW, not at GC time
            it.close()
        return pending, batch_sizes, epoch_examples, global_batch_idx, stop, work_t

    def _drain_pending(
        self, pending: "List[Dict[str, jnp.ndarray]]"
    ) -> Dict[str, np.ndarray]:
        """Bring a run of per-step device metrics to host: one stack-op +
        one transfer per metric key (per dtype when possible) for the WHOLE
        list — on a remote-attached chip each transfer is a full network
        round-trip. A mid-run reshard leaves metrics on different device
        sets, so stacking is per run of same-sharded values (still
        O(reshards) ops, not O(steps))."""
        runs: List[List[Dict[str, jnp.ndarray]]] = [[pending[0]]]
        probe = next(iter(pending[0]))
        for m in pending[1:]:
            if m[probe].sharding == runs[-1][-1][probe].sharding:
                runs[-1].append(m)
            else:
                runs.append([m])
        # The eager stacks DISPATCH under the table lock AND the
        # process-wide dispatch scope: they are multi-device
        # programs (and can carry an implicit transfer when a metric
        # landed with a different placement), and a dispatch racing
        # ANY other job's dispatches enqueues per-device work in
        # divergent orders — on backends with in-process collectives
        # that inverts a rendezvous and aborts the process
        # (parallel/dispatch.py). The D2H copies below stay outside.
        combined = None
        with self.ctx.model_table._lock:
            with dispatch_scope(self.mesh) as finish:
                stacked = finish({
                    k: [jnp.stack([m[k] for m in r]) for r in runs]
                    for k in pending[0]
                })
                if len(runs) == 1:
                    # Fold ALL same-dtype keys into one array so the
                    # drain is ONE device->host transfer per dtype, not
                    # one per key. (Multi-run drains — a mid-run reshard
                    # — keep the per-key path.)
                    keys = sorted(stacked)
                    groups: Dict[Any, List[str]] = {}
                    for k in keys:
                        # sharding in the key: sibling metrics may
                        # land on different device sets, and one
                        # eager stack over non-colocated arrays
                        # raises at dispatch
                        sig = (stacked[k][0].dtype,
                               stacked[k][0].shape,
                               stacked[k][0].sharding)
                        groups.setdefault(sig, []).append(k)
                    combined = {
                        dt: (ks, finish(jnp.stack(
                            [stacked[k][0] for k in ks])))
                        for dt, ks in groups.items()
                    }
        if combined is not None:
            host = {}
            for ks, arr in combined.values():
                mat = np.asarray(arr)          # one D2H per dtype
                for i, k in enumerate(ks):
                    host[k] = np.atleast_1d(mat[i])
        else:
            host = {
                k: np.concatenate(
                    [np.atleast_1d(np.asarray(s)) for s in v])
                for k, v in stacked.items()
            }
        return host

    def _run_batched_epochs_window(
        self, first_epoch: int, k: int, global_batch_idx: int
    ):
        """``k`` epochs of async per-batch dispatches (TaskUnit admission
        per batch is preserved — concurrent tenants still interleave at
        batch granularity) with ONE metric drain for the whole window.
        Windowable trainer hooks run between epochs, exactly as in
        :meth:`_run_fused_epochs`. Returns ([(examples, last_metrics)] per
        epoch, global_batch_idx, seconds_per_epoch)."""
        per_epoch = []
        t_start = time.perf_counter()
        for j in range(k):
            pending, sizes, examples, global_batch_idx, _stop, work_t = (
                self._dispatch_epoch_batches(first_epoch + j, global_batch_idx)
            )
            per_epoch.append((pending, sizes, examples, work_t,
                              self._take_dispatch_sec()))
            # next epoch's producer overlaps either the next dispatch run
            # (j+1 < k) or the window drain below
            self._spawn_next_pipeline(first_epoch + j + 1)
            if j + 1 < k:
                self.trainer.on_epoch_finished(self.ctx, first_epoch + j)
        all_pending = [m for p, _, _, _, _ in per_epoch for m in p]
        drain_t = 0.0
        host: Dict[str, np.ndarray] = {}
        if all_pending:
            with trace_span("dolphin.metric_drain", job_id=self.job_id,
                            epoch=first_epoch, batches=len(all_pending),
                            epochs=k):
                # the drain's stacks are dispatches; timer starts INSIDE
                # the turn (admission wait is scheduling, not work); NET
                # unit under tenancy — see _run_batched_epoch's drain
                with self._turn(), self._taskunit_scope("NET"):
                    t0 = time.perf_counter()
                    host = self._drain_pending(all_pending)
            drain_t = time.perf_counter() - t0
        out = []
        off = 0
        for pending, sizes, examples, work_t, disp_t in per_epoch:
            nb = len(pending)
            last: Dict[str, float] = {}
            if nb:
                epoch_host = {key: v[off:off + nb] for key, v in host.items()}
                last = self._emit_batch_metrics(
                    first_epoch + len(out), epoch_host, sizes,
                    (work_t + drain_t / k) / nb,
                    dispatch_sec=disp_t,
                )
            off += nb
            # accounting deferred to run()'s replay loop (see
            # _run_fused_epochs) so ServerMetrics deltas stay per-epoch
            out.append((examples, last, nb))
        per_epoch_sec = (time.perf_counter() - t_start) / k
        return out, global_batch_idx, per_epoch_sec

    def _take_dispatch_sec(self) -> float:
        """Drain the host-dispatch accumulator (one epoch's placement
        seconds; single-threaded — only the training thread feeds it)."""
        v, self._phase_dispatch_acc = self._phase_dispatch_acc, 0.0
        return v

    def _emit_batch_metrics(
        self,
        epoch: int,
        host: Dict[str, np.ndarray],
        batch_sizes: List[int],
        per_batch_time: float,
        dispatch_sec: float = 0.0,
        dispatch_in_work: bool = True,
    ) -> Dict[str, float]:
        """Shared epoch-end drain: strip internal underscore-keys (_sync),
        emit one BatchMetrics per batch with the smeared time, and return
        the final batch's metrics as floats."""
        if "_dropped" in host:
            # keys the sparse table refused mid-training: fold into the
            # table's cumulative overflow counter (never silent). "_dropped"
            # is only emitted by the hash-table step, so the concrete type
            # is known — no defensive getattr that could silently detach
            # the counter.
            n = int(np.sum(host["_dropped"]))
            if n:
                self.ctx.model_table.count_dropped(n)
        host = {k: v for k, v in host.items() if not k.startswith("_")}
        # one shared fallback rule (_primary_key) for the per-batch series
        lkey = self._primary_key(host)
        losses = host[lkey] if lkey is not None else np.zeros(len(batch_sizes))
        # honest comm/comp split from the last probe (see _probe_comm):
        # comp = measured step time minus the probed pull/push device time.
        # With the probe off both are 0 and comp degenerates to the whole
        # batch time — the conservative fused-mode default. The unfused
        # per-phase path needs no probe at all: its phases dispatch
        # separately, so the split is MEASURED per step.
        measured_fn = getattr(self._step, "mean_phase_seconds", None)
        measured = measured_fn() if measured_fn is not None else None
        if measured is not None:
            t_pull, _t_comp, t_push = measured
        else:
            t_pull, t_push = (self.ctx.model_table.comm_split()
                              or self._comm_probe_times)
        comp = max(per_batch_time - t_pull - t_push, 0.0)
        # NOTE: the weighted-fair-queue unit cost is reported from the
        # dispatch scope only (per granted UNIT) — reporting the drain's
        # per-BATCH smear here would mix scales differing by the group
        # factor and undercharge grouped jobs.
        for b, n in enumerate(batch_sizes):
            self.collector.add(
                BatchMetrics(
                    job_id=self.job_id,
                    worker_id=self.ctx.worker_id,
                    epoch_idx=epoch,
                    batch_idx=b,
                    num_examples=n,
                    batch_time_sec=per_batch_time,
                    pull_time_sec=t_pull,
                    comp_time_sec=comp,
                    push_time_sec=t_push,
                    loss=float(losses[b]),
                )
            )
        # per-tenant step-time histogram (/metrics exposition + the
        # straggler report's raw material): one observation per batch at
        # the smeared per-batch time — async dispatch makes true
        # per-batch device time unobservable (see the drain docstrings)
        hist = self._step_histogram()
        if hist is not None:
            for _ in batch_sizes:
                hist.observe(per_batch_time)
        # tenant cost ledger (metrics/accounting.py): one feed per epoch
        # drain — device seconds, steps, examples, the compiled step's
        # FLOP figure, and the current resident-HBM components. Guarded:
        # accounting must never fail (or slow) the drain.
        try:
            from harmony_tpu.metrics.accounting import ledger

            steps = len(batch_sizes)
            acct = ledger()
            acct.observe_steps(
                self.job_id, self.attempt_key, self.ctx.worker_id,
                steps=steps, device_sec=per_batch_time * steps,
                examples=int(sum(batch_sizes)),
                flops_per_step=self._program_flops_per_step(),
                devices=int(self.mesh.devices.size),
            )
            acct.set_resident(self.job_id, self.attempt_key, "input",
                              self._input_resident_bytes())
            acct.set_resident(self.job_id, self.attempt_key, "program",
                              self._program_resident_bytes())
            # async lever state: availability tells the policy engine the
            # lever EXISTS for this tenant; when enabled, the staleness
            # telemetry shows overlapped vs exposed comm time
            stats_fn = getattr(self._step, "staleness_stats", None)
            stats = stats_fn() if stats_fn is not None else None
            acct.set_async_state(
                self.job_id, self.attempt_key,
                available=self._async_capable(),
                enabled=stats is not None,
                bound=(stats["bound"] if stats is not None
                       else self._staleness_bound),
                max_lag=(stats or {}).get("max_lag", 0),
                exposed_wait_sec=(stats or {}).get("exposed_wait_sec", 0.0),
                overlapped_comm_sec=(stats or {}).get(
                    "overlapped_comm_sec", 0.0),
            )
        except Exception:
            pass
        # Step-phase time budget (metrics/phases.py): split this epoch's
        # measured work into pull/compute/push — the unfused step's REAL
        # per-phase measurements, else the probe split refined by the
        # compiled program's FLOP seconds — and stage it (with the
        # host-dispatch seconds) for _finish_epoch, where the epoch WALL
        # is known and the budget feeds. Guarded: the budget must never
        # fail (or slow) the drain.
        try:
            from harmony_tpu.metrics.accounting import _peak_flops
            from harmony_tpu.metrics.phases import split_device_phases

            steps = len(batch_sizes)
            work = per_batch_time * steps
            split = split_device_phases(
                work, steps,
                # batched paths time placement INSIDE the per-batch dt
                # (subtract it from the work split); the fused-epoch
                # path's stacked upload happens OUTSIDE work_t
                dispatch_sec=dispatch_sec if dispatch_in_work else 0.0,
                measured=measured,
                probe_split=(None if measured is not None
                             else (t_pull, t_push)),
                flops_per_step=self._program_flops_per_step(),
                peak_flops=_peak_flops(),
                devices=int(self.mesh.devices.size),
            )
            self._phase_pending[epoch] = {
                "host_dispatch": float(dispatch_sec), **split}
        except Exception:
            pass
        return {k: float(v[-1]) for k, v in host.items()}

    # -- tenant cost accounting helpers ----------------------------------

    def _program_flops_per_step(self) -> Optional[float]:
        """XLA cost-analysis FLOPs of ONE step of the current program
        (runtime/progcache's compile telemetry), resolved lazily — the
        cost row exists only after the first dispatch compiled. The
        fused-epoch program's figure covers the whole scan, so it is
        divided back down to per-step. None (never 0.0) when the
        backend exposes no cost model or the trainer opted out of
        caching."""
        if self._flops_per_step is not None:
            return self._flops_per_step
        key = self._program_cache_key
        if key is None:
            return None
        if not self._fused_mode():
            total = 0.0
            for tag in ("unfused_pull", "unfused_comp", "unfused_push"):
                cost = progcache.program_cost((key, tag))
                if cost is None or cost.flops is None:
                    return None
                total += cost.flops
            self._flops_per_step = total
        elif self._use_fused_epoch():
            cost = progcache.program_cost((key, "epoch"))
            if cost is None or cost.flops is None:
                return None
            self._flops_per_step = cost.flops / max(
                self.data.num_mini_batches, 1)
        else:
            cost = progcache.program_cost((key, "step"))
            if cost is None or cost.flops is None:
                return None
            self._flops_per_step = cost.flops
        return self._flops_per_step

    def _table_resident_bytes(self) -> int:
        """Device bytes pinned by this job's table storage (dense
        array, or hash keys+values) — the dominant HBM term for table
        workloads."""
        def one(table) -> int:
            if table is None:
                return 0
            spec = table.spec
            itemsize = np.dtype(spec.dtype).itemsize
            kshape = getattr(spec, "keys_shape", None)
            if kshape is not None:  # hash table: int32 keys + values
                return (int(np.prod(kshape)) * 4
                        + int(np.prod(spec.values_shape)) * itemsize)
            return int(np.prod(spec.storage_shape)) * itemsize

        total = one(self.ctx.model_table)
        if self.trainer.uses_local_table:
            total += one(self.ctx.local_table)
        return total

    def _input_resident_bytes(self) -> int:
        """Device bytes of this worker's resident input copies (its
        stacked-epoch upload + per-batch caches — the worker's share of
        devcache occupancy)."""
        total = 0
        if self._stacked_cache is not None:
            total += sum(int(getattr(a, "nbytes", 0))
                         for a in self._stacked_cache)
        for b in self._batch_cache.values():
            leaves = b if isinstance(b, (tuple, list)) else (b,)
            total += sum(int(getattr(a, "nbytes", 0)) for a in leaves)
        return total

    def _program_resident_bytes(self) -> int:
        """Temp + generated-code bytes of this job's compiled programs
        (memory_analysis via progcache) — the constants/workspace HBM a
        compiled executable pins beyond its arguments."""
        key = self._program_cache_key
        if key is None:
            return 0
        total = 0
        for tag in ("step", "epoch", "eval",
                    "unfused_pull", "unfused_comp", "unfused_push"):
            cost = progcache.program_cost((key, tag))
            if cost is not None:
                total += ((cost.temp_bytes or 0)
                          + (cost.generated_code_bytes or 0))
        return total

    def _step_histogram(self):
        """Cached child of harmony_step_time_seconds for this worker's
        (job, attempt, worker) labelset; None when the registry is
        unusable (metrics must never fail the hot loop)."""
        hist = getattr(self, "_step_hist", None)
        if hist is None:
            try:
                from harmony_tpu.metrics.registry import (
                    STEP_TIME_BUCKETS,
                    get_registry,
                )

                hist = get_registry().histogram(
                    "harmony_step_time_seconds",
                    "Per-mini-batch dispatch+device seconds per worker",
                    ("job", "attempt", "worker"),
                    buckets=STEP_TIME_BUCKETS,
                ).labels(job=self.job_id, attempt=self.attempt_key,
                         worker=self.ctx.worker_id)
            except Exception:
                return None
            self._step_hist = hist
        return hist

    def _ensure_stacked_cache(self) -> None:
        """Device-resident whole-epoch dataset ([num_batches, batch, ...]
        per array), rebuilt after any reshard cleared it (the stack must
        live on the table's CURRENT mesh)."""
        if self._stacked_cache is not None:
            return
        table = self.ctx.model_table
        gkey = self._devcache_key("stacked")
        hit = devcache.get(gkey) if gkey is not None else None
        if hit is not None:
            self._stacked_cache = hit
            return
        with trace_span("dolphin.dataset_upload", job_id=self.job_id):
            batches = list(self.data.epoch_batches())
            stacked_sharding = NamedSharding(table.mesh, P(None, DATA_AXIS))
            self._stacked_cache = tuple(
                jax.device_put(np.stack([b[i] for b in batches]),
                               stacked_sharding)
                for i in range(len(batches[0]))
            )
        devcache.put(gkey, self._stacked_cache)

    def _dispatch_epoch_fn(self):
        """One whole-epoch dispatch (see _build_step), retried across
        concurrent reshards. Returns the epoch's stacked device metrics."""
        for _ in range(self.MAX_RESHARD_RETRIES):
            self._maybe_rebuild()
            self._ensure_stacked_cache()
            try:
                return self._dispatch_step(self._epoch_fn, self._stacked_cache)
            except ValueError as e:
                if not self._is_layout_race(e):
                    raise
                self._build_step()  # force-rebuild (see _dispatch_batch)
        raise RuntimeError(
            f"table resharded {self.MAX_RESHARD_RETRIES}x during one "
            "epoch dispatch; reconfiguration is outpacing training"
        )

    def _run_fused_epochs(
        self, first_epoch: int, k: int
    ) -> "Tuple[List[Tuple[int, Dict[str, float]]], float]":
        """``k`` whole-epoch dispatches chained on the table state with ONE
        drain at the end (k=1 = the plain fused epoch). Windowable trainer
        hooks run BETWEEN dispatches so epoch-indexed hyperparams (decay,
        PRNG folds) feed each dispatch exactly as in the unfused loop.
        Returns ([(examples, last_metrics)] per epoch, seconds_per_epoch)."""
        # cache build BEFORE the timer starts: the one-time dataset
        # stacking/transfer must not inflate per-batch times fed to the
        # optimizer (a mid-window reshard rebuilds it inside the retry
        # loop and does count — it IS reconfiguration cost). Inside a
        # TURN: on multi-process backends a device_put onto a sharding
        # that replicates across processes is itself collective-backed
        # (gloo pairs the transfers), so two tenants' uploads
        # interleaving with steps produce a cross-process collective
        # mismatch — any global placement must hold the dispatch unit.
        with self._turn():
            # the one-time stacked upload is this path's host-dispatch
            # phase: the host work between batches-ready and device
            # dispatch (zero on warm-cache windows)
            t_place = time.perf_counter()
            self._ensure_stacked_cache()
            self._phase_dispatch_acc += time.perf_counter() - t_place
        dispatch_sec = self._take_dispatch_sec()
        work_t = 0.0  # dispatch+device seconds, EXCLUDING admission waits
        window_metrics = []
        for j in range(k):
            # each whole-epoch dispatch is one admission turn / pod unit:
            # its enqueues must not interleave with another tenant's. The
            # timer starts INSIDE the turn — a co-tenant's unit wait is
            # scheduling, not work, and must not inflate the per-batch
            # times feeding the optimizer's cost model (same rule as the
            # batched path's scopes).
            with self._turn():
                t0 = time.perf_counter()
                window_metrics.append(self._dispatch_epoch_fn())
                work_t += time.perf_counter() - t0
            if j + 1 < k:
                # windowable by declaration: depends only on the epoch
                # index, so it may run before the epoch's results drain
                self.trainer.on_epoch_finished(self.ctx, first_epoch + j)
        # ONE drain for the whole window, counted as work: the per-batch
        # times fed to the optimizer must include device execution, and on
        # a lazy backend block_until_ready would stop the clock at dispatch
        t_sync = time.perf_counter()
        hard_sync(window_metrics)
        work_t += time.perf_counter() - t_sync
        per_epoch_sec = work_t / k
        nb = self.data.num_mini_batches
        out = []
        for j, stacked_metrics in enumerate(window_metrics):
            host_metrics = {
                key: np.atleast_1d(np.asarray(v))
                for key, v in stacked_metrics.items()
            }
            last = self._emit_batch_metrics(
                first_epoch + j, host_metrics,
                [self.data.batch_size] * nb, per_epoch_sec / nb,
                dispatch_sec=dispatch_sec / k, dispatch_in_work=False,
            )
            # op accounting happens in run()'s replay loop, interleaved
            # with the deferred epoch callbacks, so per-epoch ServerMetrics
            # deltas stay per-epoch instead of lumping onto the window's
            # first report
            out.append((self.data.num_examples, last, nb))
        return out, per_epoch_sec

    def _primary_key(self, metrics) -> Optional[str]:
        """The ONE key that is this job's progress scalar: 'loss', else the
        trainer's declared objective_metric (e.g. LDA's log_likelihood).
        Other metric keys are counters — never relabeled as a loss."""
        if "loss" in metrics:
            return "loss"
        om = self.trainer.objective_metric
        return om if om and om in metrics else None

    def _primary_metric(self, metrics: Dict[str, float]) -> float:
        k = self._primary_key(metrics)
        return float(metrics[k]) if k is not None else 0.0

    def _finish_epoch(self, epoch, epoch_t0, epoch_examples, last_metrics,
                      epoch_losses, call_trainer_hook: bool = True):
        # epoch-boundary fault site: the fused/windowed paths dispatch
        # whole epochs without per-batch host steps, so this is the
        # boundary every path crosses (checkpoint hooks fire right after)
        if faults.armed():
            faults.site(
                "worker.epoch", job=self.job_id, worker=self.ctx.worker_id,
                epoch=epoch, proc=jax.process_index(),
            )
        progress = self._primary_metric(last_metrics)
        epoch_sec = time.perf_counter() - epoch_t0
        self.collector.add(
            EpochMetrics(
                job_id=self.job_id,
                worker_id=self.ctx.worker_id,
                epoch_idx=epoch,
                num_examples=epoch_examples,
                epoch_time_sec=epoch_sec,
                loss=progress,
            )
        )
        try:  # per-tenant epoch-time histogram for /metrics scrapers
            from harmony_tpu.metrics.registry import (
                EPOCH_TIME_BUCKETS,
                get_registry,
            )

            get_registry().histogram(
                "harmony_epoch_time_seconds",
                "Per-epoch wall seconds per worker",
                ("job", "attempt"),
                buckets=EPOCH_TIME_BUCKETS,
            ).labels(job=self.job_id, attempt=self.attempt_key).observe(
                epoch_sec)
        except Exception:
            pass
        # Step-phase budget feed: the epoch wall is finally known here —
        # join the staged work split + host-dispatch with this epoch's
        # input-wait and hand the row to the process budget store
        # (metrics/phases.py). Whatever the measured phases do not cover
        # (admission waits, drains' host share, this bookkeeping) stays
        # an explicit residual there. Guarded: the budget must never
        # fail the epoch boundary.
        # popped UNCONDITIONALLY, outside the guard: the stream close
        # stages an input-wait entry per epoch, and a failing split or
        # budget path must not grow these dicts by one orphan per
        # epoch for the life of the tasklet
        ph = self._phase_pending.pop(epoch, None)
        input_wait = self._phase_input_wait.pop(epoch, 0.0)
        if ph is not None:
            try:
                from harmony_tpu.metrics.phases import budget

                ph["input_wait"] = input_wait
                budget().observe_epoch(
                    self.job_id, self.attempt_key, self.ctx.worker_id,
                    epoch, epoch_sec, ph)
            except Exception:
                pass
        self._check_slo(epoch, epoch_examples, epoch_sec)
        epoch_losses.append(progress)
        if call_trainer_hook:
            self.trainer.on_epoch_finished(self.ctx, epoch)
        # The callback may dispatch global programs (pod checkpoint
        # chains, plan-driven block moves) — under pod tenancy it holds a
        # turn/unit. Turn balance: non-chief workers take a matching
        # no-op turn so the strict rotation stays aligned (see run()).
        if self.epoch_callback is not None:
            with self._turn():
                self.epoch_callback(epoch)
        elif self._balanced_turns():
            with self._turn():
                pass
        self.collector.flush()

    #: consecutive under-target epochs before the SLO event fires — one
    #: slow epoch (a reshard, a checkpoint, a co-tenant's burst) is
    #: noise; a sustained run is the scheduler-actionable signal
    SLO_WINDOW_EPOCHS = 3
    #: attainment floor: below this fraction of target counts as a breach
    SLO_ATTAINMENT_FLOOR = 0.9

    def _check_slo(self, epoch: int, epoch_examples: int,
                   epoch_sec: float) -> None:
        """Windowed SLO attainment check at the epoch boundary (chief
        only — the target is per JOB, so sibling workers checking their
        own shares would multiply-fire). The job-level rate is estimated
        as this worker's rate × num_workers (the data provider splits
        the epoch evenly); exact for single-worker jobs. A sustained
        breach records ONE structured joblog event (kind="slo") and
        counts in the tenant ledger; recovery above the floor re-arms."""
        if self._slo_target is None or not self.global_init:
            return
        own_sps = epoch_examples / epoch_sec if epoch_sec > 0 else 0.0
        job_sps = own_sps * max(self.ctx.num_workers, 1)
        if job_sps >= self.SLO_ATTAINMENT_FLOOR * self._slo_target:
            self._slo_below = 0
            self._slo_fired = False
            return
        self._slo_below += 1
        if self._slo_below < self.SLO_WINDOW_EPOCHS or self._slo_fired:
            return
        self._slo_fired = True
        try:
            from harmony_tpu.jobserver import joblog
            from harmony_tpu.metrics.accounting import ledger

            joblog.record_event(
                self.job_id, kind="slo",
                attempt=self.attempt_key,
                epoch=epoch,
                target_sps=self._slo_target,
                achieved_sps=round(job_sps, 3),
                attainment=round(job_sps / self._slo_target, 4),
                window_epochs=self.SLO_WINDOW_EPOCHS,
            )
            ledger().record_slo_event(self.job_id)
        except Exception:
            pass  # SLO observability never fails the epoch boundary

    def _account_ops(self, num_steps: int) -> None:
        """Fold this dispatch window's pull/push counts (one pull + one push
        per fused step) into this worker's own counters — per-job metric
        attribution sums the job's workers, so jobs sharing one table never
        double-count each other's traffic."""
        spec = self.ctx.model_table.spec
        row_bytes = int(np.prod(spec.value_shape)) * spec.dtype.itemsize if spec.value_shape else spec.dtype.itemsize
        self.op_stats["pulls"] += num_steps
        self.op_stats["pushes"] += num_steps
        self.op_stats["pull_bytes"] += num_steps * self._pull_rows * row_bytes

    def _taskunit_scope(self, kind: str):
        if self.taskunit is None:
            return contextlib.nullcontext()
        return self.taskunit.scope(kind)

    def _turn(self):
        """This worker's turnstile admission (pod lockstep), else a no-op."""
        if self.dispatch_turn is None:
            return contextlib.nullcontext()
        return self.dispatch_turn()

    def _balanced_turns(self) -> bool:
        """True when this worker must take no-op turns to keep the cyclic
        turnstile's strict rotation aligned with its siblings' chief-only
        turns (multi-worker turnstiled jobs; single-thread jobs have no
        rotation to balance)."""
        return self.dispatch_turn is not None and self.ctx.num_workers > 1

    # -- evaluation (ref: ModelEvaluator over checkpointed models) -------

    def evaluate(self, batch: Tuple[np.ndarray, ...]) -> Dict[str, float]:
        from harmony_tpu.table.hashtable import DeviceHashTable

        table = self.ctx.model_table
        if isinstance(table, DeviceHashTable):
            raise NotImplementedError(
                "full-model evaluate is undefined over an unbounded key "
                "domain; evaluate a sparse model through its keyed pull "
                "(trainer.compute-style) or train with a dense table"
            )
        if self._eval_fn is None:
            self._eval_fn = jax.jit(self.trainer.evaluate)
        model = table.pull_array()
        metrics = self._eval_fn(model, self._shard_batch(batch))
        return {k: float(v) for k, v in metrics.items()}


class FusedSparseStep:
    """ONE compiled program for a host-driven sparse pull→compute→push.

    The host path (ModelAccessor users: benchmarks, serving-style readers,
    apps driving a table outside WorkerTasklet) historically crossed
    Python per phase — ``pull`` gathers to numpy, the caller computes, and
    ``push`` scatters the delta back, three dispatches and two full host
    round-trips per batch. This wraps the cycle the way the dense SPMD
    fast path does (WorkerTasklet._program_builders): the table array
    enters as a DONATED argument, the keyed gather / compute / keyed
    scatter trace into one XLA program, and dispatch+commit ride
    ``DenseTable.apply_step`` so donation stays invisible to concurrent
    host accessors. Underneath, the keyed gather/scatter lower through
    ops/sparse.py (Pallas on TPU, jnp fallback elsewhere).

    Phase accounting matches the accessor's documented fused contract:
    the WHOLE step is charged to COMP (``comp_tracer`` feeds the
    ``harmony_phase_seconds{phase="accessor.comp"}`` histogram); the
    pull/push tracers genuinely have no separable phases to report.

    Donation rules: ONLY the table buffer (argument 0) is donated. Keys
    and extra operands — including device arrays staged by
    :meth:`run_batches` or held in the process devcache — are read-only
    by construction, preserving the devcache contract
    (data/devcache.py: cached buffers are never invalidated by a step).

    ``signature`` (hashable) names the compute_fn's traced behavior for
    the process program cache (runtime/progcache) — same contract as
    ``Trainer.jit_signature``: equal signatures MUST mean an identical
    traced program, and the default ``None`` opts out of caching.
    """

    #: steps in flight before the driver blocks on the oldest aux (keeps
    #: the donated-buffer chain and dispatch queue bounded)
    MAX_INFLIGHT = 8

    def __init__(
        self,
        table,
        compute_fn: Callable,
        *,
        signature: Optional[Any] = None,
        donate: bool = True,
        push_via: Optional[str] = None,
    ) -> None:
        from harmony_tpu.metrics.tracer import Tracer
        from harmony_tpu.table.hashtable import DeviceHashTable
        from harmony_tpu.table.table import DenseTable

        if isinstance(table, DeviceHashTable):
            raise TypeError(
                "FusedSparseStep drives DenseTable workloads; hash-backed "
                "tables already fuse through WorkerTasklet's keyed step"
            )
        if not isinstance(table, DenseTable):
            raise TypeError(f"need a DenseTable, got {type(table).__name__}")
        self.table = table
        spec = table.spec
        route = push_via if push_via is not None else table.push_via
        self.push_route = route
        self.donate = bool(donate)

        def _step(arr, keys, *extra):
            rows = spec.pull(arr, keys)                    # PULL
            delta, aux = compute_fn(rows, *extra)          # COMP
            new_arr = spec.push(arr, keys, delta, via=route)  # PUSH
            return new_arr, aux

        dn = (0,) if donate else ()
        key = None
        if signature is not None:
            from harmony_tpu.runtime import progcache as _pc

            tsig = _pc.table_signature(table)
            if tsig is not None:
                key = (tsig, "fused_sparse", signature, route, bool(donate))
        self._fn = progcache.get_or_build(
            key, lambda: jax.jit(_step, donate_argnums=dn)
        )
        self.cache_key = key
        self.comp_tracer = Tracer(instrument="accessor.comp")

    # -- single step ------------------------------------------------------

    def step(self, keys, *extra):
        """Dispatch one fused batch and commit; returns compute_fn's aux.
        Blocks on the aux (the accessor's per-op shape) so the tracer
        charges real device time to COMP."""
        k = keys if hasattr(keys, "dtype") else jnp.asarray(keys, jnp.int32)
        self.comp_tracer.start()
        aux = self.table.apply_step(self._fn, k, *extra)
        self.comp_tracer.record(int(k.shape[0]), block_on=aux)
        return aux

    # -- batched driver with double-buffered staging ----------------------

    def _stage(self, batch: Tuple) -> Tuple:
        """H2D placement of one host batch (keys first, then compute_fn's
        extras), replicated on the table's mesh. Staged arrays are only
        ever read by the step (never donated)."""
        mesh = self.table.mesh
        sh = NamedSharding(mesh, P())
        keys, *extra = batch
        k = keys if hasattr(keys, "dtype") else np.asarray(keys, np.int32)
        return tuple(jax.device_put(a, sh) for a in (k, *extra))

    def run_batches(self, batches, *, inflight: Optional[int] = None):
        """Drive host batches ``(keys, *extra)`` through the fused step
        with batch k+1's device_put STAGED while batch k computes — the
        double-buffered gradient/index transfer (StageRing, the input
        pipeline's primitive). Returns the list of per-batch aux outputs
        (synced). Falls back to synchronous staging on multi-process
        meshes, where a background device_put is collective-backed (same
        rule as WorkerTasklet._prefetch_usable)."""
        from harmony_tpu.data.loader import StageRing
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        cap = int(inflight) if inflight else 2
        auxes: List[Any] = []
        if mesh_spans_processes(self.table.mesh):
            for b in batches:
                auxes.append(self.table.apply_step(self._fn, *self._stage(b)))
            hard_sync(auxes)
            return auxes
        ring = StageRing(lambda: cap)

        def produce() -> None:
            try:
                for b in batches:
                    if not ring.put(self._stage(b)):
                        return
                ring.finish()
            except BaseException as e:  # surfaced at the consumer's get()
                ring.set_error(e)

        t = threading.Thread(target=produce, daemon=True,
                             name="fused-sparse-stage")
        t.start()
        try:
            while True:
                item = ring.get()
                if item is StageRing.DONE:
                    break
                auxes.append(self.table.apply_step(self._fn, *item))
                if len(auxes) >= self.MAX_INFLIGHT:
                    hard_sync(auxes[len(auxes) - self.MAX_INFLIGHT])
        finally:
            ring.close()
            t.join(timeout=5.0)
        hard_sync(auxes)
        return auxes
