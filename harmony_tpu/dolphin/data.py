"""Training data provisioning: epoch = local partition, mini-batch = block.

Parity with ETTrainingDataProvider (dolphin/core/worker/
ETTrainingDataProvider.java:38-75): an epoch iterates the worker's local
partition of the input table; one mini-batch is one block; the number of
blocks per worker (NumWorkerBlocks) fixes the batch count.

TPU-first realization: the input set is host numpy arrays (features/labels),
pre-split into ``num_mini_batches`` equal blocks. In SPMD mode a "batch" is
the *global* batch for one step — the framework shards it over the mesh data
axis, so each chip (the analogue of one worker) sees its local slice, exactly
like each reference worker seeing its local input blocks.
"""
from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np


class TrainingDataProvider:
    """Splits an in-memory dataset into per-epoch mini-batches."""

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        num_mini_batches: int,
        shuffle_each_epoch: bool = False,
        seed: int = 0,
        dataset_key: "tuple | None" = None,
    ) -> None:
        # Identity of the DATA SOURCE (generator path + args + worker slice),
        # set by the job entity: stable batches with a key participate in the
        # process-level device cache (data/devcache.py) so resubmitted jobs
        # reuse device-resident copies. None (the default) = private data.
        self.dataset_key = dataset_key if not shuffle_each_epoch else None
        if not arrays:
            raise ValueError("need at least one data array")
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("all data arrays must share leading dim")
        if num_mini_batches <= 0 or num_mini_batches > n:
            raise ValueError(f"bad num_mini_batches={num_mini_batches} for n={n}")
        # Trim to an equal split so every batch has a static shape (XLA
        # recompiles on shape change; the reference tolerated ragged blocks,
        # we deliberately don't).
        self.batch_size = n // num_mini_batches
        self.num_mini_batches = num_mini_batches
        self._arrays = [a[: self.batch_size * num_mini_batches] for a in arrays]
        self._shuffle = shuffle_each_epoch
        self._rng = np.random.default_rng(seed)

    @property
    def num_examples(self) -> int:
        return self.batch_size * self.num_mini_batches

    @property
    def is_shuffling(self) -> bool:
        return self._shuffle

    def epoch_batches(self) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield ``num_mini_batches`` tuples of per-batch arrays.

        The permutation gather is applied ONCE per array per epoch (one
        contiguous pass), then batches are sliced as views — per-batch
        fancy indexing re-walked the whole index array for every batch and
        dominated host-side input cost on large datasets. Non-shuffling
        epochs skip the gather entirely and yield pure views (consumers
        never mutate batches: they feed ``np.stack``/``device_put``).

        Memory: a shuffling epoch holds ONE dataset-sized permuted copy
        for the epoch's duration (the same total bytes the per-batch
        gathers allocated, resident at once instead of batch-at-a-time),
        and the prefetcher's cross-epoch overlap can briefly keep two
        epochs' copies alive — hosts sized tightly to the dataset should
        disable shuffling or ``input_prefetch``."""
        if self._shuffle:
            idx = np.arange(self.num_examples)
            self._rng.shuffle(idx)
            epoch_arrays = [a[idx] for a in self._arrays]
        else:
            epoch_arrays = self._arrays
        for b in range(self.num_mini_batches):
            sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
            yield tuple(a[sl] for a in epoch_arrays)

    def batch_at(self, b: int) -> Tuple[np.ndarray, ...]:
        """Batch ``b`` of the STABLE epoch order — only defined for
        non-shuffling providers (shuffled order lives in the epoch
        iterator's RNG draw). Used to re-materialize a host batch when a
        device cache entry was invalidated by a live reshard."""
        if self._shuffle:
            raise ValueError("batch_at is undefined for shuffling providers")
        if not 0 <= b < self.num_mini_batches:
            raise IndexError(f"batch {b} out of range")
        sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
        return tuple(a[sl] for a in self._arrays)
