"""Training data provisioning: epoch = local partition, mini-batch = block.

Parity with ETTrainingDataProvider (dolphin/core/worker/
ETTrainingDataProvider.java:38-75): an epoch iterates the worker's local
partition of the input table; one mini-batch is one block; the number of
blocks per worker (NumWorkerBlocks) fixes the batch count.

TPU-first realization: the input set is host numpy arrays (features/labels),
pre-split into ``num_mini_batches`` equal blocks. In SPMD mode a "batch" is
the *global* batch for one step — the framework shards it over the mesh data
axis, so each chip (the analogue of one worker) sees its local slice, exactly
like each reference worker seeing its local input blocks.
"""
from __future__ import annotations

from typing import Any, Iterator, Sequence, Tuple

import numpy as np


class TrainingDataProvider:
    """Splits an in-memory dataset into per-epoch mini-batches."""

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        num_mini_batches: int,
        shuffle_each_epoch: bool = False,
        seed: int = 0,
        dataset_key: "tuple | None" = None,
    ) -> None:
        # Identity of the DATA SOURCE (generator path + args + worker slice),
        # set by the job entity: stable batches with a key participate in the
        # process-level device cache (data/devcache.py) so resubmitted jobs
        # reuse device-resident copies. None (the default) = private data.
        self.dataset_key = dataset_key if not shuffle_each_epoch else None
        if not arrays:
            raise ValueError("need at least one data array")
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("all data arrays must share leading dim")
        if num_mini_batches <= 0 or num_mini_batches > n:
            raise ValueError(f"bad num_mini_batches={num_mini_batches} for n={n}")
        # Trim to an equal split so every batch has a static shape (XLA
        # recompiles on shape change; the reference tolerated ragged blocks,
        # we deliberately don't).
        self.batch_size = n // num_mini_batches
        self.num_mini_batches = num_mini_batches
        self._arrays = [a[: self.batch_size * num_mini_batches] for a in arrays]
        self._shuffle = shuffle_each_epoch
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        # Replay cursor for epoch_batches_at: (next_epoch, rng) of a
        # SEPARATE generator advanced only by explicit-epoch reads, so
        # random-access epochs stay O(1) amortized when consumed in
        # order (the service/fallback path) without touching the
        # sequential iterator's RNG. Lock-guarded: concurrent explicit-
        # epoch readers exist on the trainer side (a pump thread's
        # fallback racing a self-serving consumer, or a pre-spawned
        # next-epoch producer) and an interleaved shuffle draw would
        # silently yield the WRONG permutation.
        import threading

        self._replay = (0, np.random.default_rng(seed))
        self._replay_lock = threading.Lock()

    @property
    def num_examples(self) -> int:
        return self.batch_size * self.num_mini_batches

    @property
    def is_shuffling(self) -> bool:
        return self._shuffle

    def epoch_batches(self) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield ``num_mini_batches`` tuples of per-batch arrays.

        The permutation gather is applied ONCE per array per epoch (one
        contiguous pass), then batches are sliced as views — per-batch
        fancy indexing re-walked the whole index array for every batch and
        dominated host-side input cost on large datasets. Non-shuffling
        epochs skip the gather entirely and yield pure views (consumers
        never mutate batches: they feed ``np.stack``/``device_put``).

        Memory: a shuffling epoch holds ONE dataset-sized permuted copy
        for the epoch's duration (the same total bytes the per-batch
        gathers allocated, resident at once instead of batch-at-a-time),
        and the prefetcher's cross-epoch overlap can briefly keep two
        epochs' copies alive — hosts sized tightly to the dataset should
        disable shuffling or ``input_prefetch``."""
        if self._shuffle:
            idx = np.arange(self.num_examples)
            self._rng.shuffle(idx)
            epoch_arrays = [a[idx] for a in self._arrays]
        else:
            epoch_arrays = self._arrays
        for b in range(self.num_mini_batches):
            sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
            yield tuple(a[sl] for a in epoch_arrays)

    def array_specs(self) -> "list[tuple[tuple, np.dtype]]":
        """Per-array (trailing shape, dtype) — the batch structure
        without the batch axis. Program keys and shape probes read THIS
        instead of poking ``_arrays``, so a deferred provider can answer
        without materializing its data."""
        return [(tuple(a.shape[1:]), a.dtype) for a in self._arrays]

    def first_rows(self, k: int) -> Tuple[np.ndarray, ...]:
        """The first ``k`` rows of each array in stable storage order
        (the comm probe's sample batch — real values, not shapes)."""
        return tuple(a[:k] for a in self._arrays)

    def epoch_permutation(self, epoch: int) -> np.ndarray:
        """The permutation ``epoch_batches()`` would draw for its
        ``epoch``-th call (0-based), WITHOUT advancing the sequential
        iterator's RNG — the epoch shuffle is thus a pure function of
        ``(seed, epoch)``, which is what lets the input service assemble
        any tenant's epoch remotely and lets a mid-job fallback resume
        at the right epoch: both replay the same draw sequence a fresh
        ``default_rng(seed)`` yields. Consumed-in-order reads are O(1)
        amortized via the replay cursor; a backward read replays from
        the seed."""
        if not self._shuffle:
            raise ValueError("epoch_permutation is undefined without shuffle")
        with self._replay_lock:
            nxt, rng = self._replay
            if epoch < nxt:  # backward: replay from scratch
                nxt, rng = 0, np.random.default_rng(self.seed)
            idx = np.arange(self.num_examples)
            while True:
                perm = idx.copy()
                rng.shuffle(perm)
                nxt += 1
                if nxt > epoch:
                    break
            self._replay = (nxt, rng)
            return perm

    def epoch_batches_at(self, epoch: int) -> Iterator[Tuple[np.ndarray, ...]]:
        """``epoch_batches()`` for an EXPLICIT epoch index: yields the
        exact batch sequence the sequential iterator's ``epoch``-th call
        yields, leaving the sequential RNG untouched. This is the
        assembly path of the input service (workers are asked for
        '(spec, epoch)', not 'next') and of the trainer's in-process
        fallback after a service give-up (the local RNG never advanced
        while the service was serving, so sequential iteration would
        replay epoch 0's draw)."""
        if self._shuffle:
            perm = self.epoch_permutation(epoch)
            epoch_arrays = [a[perm] for a in self._arrays]
        else:
            epoch_arrays = self._arrays
        for b in range(self.num_mini_batches):
            sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
            yield tuple(a[sl] for a in epoch_arrays)

    def batch_at(self, b: int) -> Tuple[np.ndarray, ...]:
        """Batch ``b`` of the STABLE epoch order — only defined for
        non-shuffling providers (shuffled order lives in the epoch
        iterator's RNG draw). Used to re-materialize a host batch when a
        device cache entry was invalidated by a live reshard."""
        if self._shuffle:
            raise ValueError("batch_at is undefined for shuffling providers")
        if not 0 <= b < self.num_mini_batches:
            raise IndexError(f"batch {b} out of range")
        sl = slice(b * self.batch_size, (b + 1) * self.batch_size)
        return tuple(a[sl] for a in self._arrays)


class DeferredTrainingDataProvider(TrainingDataProvider):
    """A provider whose host arrays materialize on FIRST data access.

    Input-service tenants consume assembled batches off the wire, so the
    local copy of the dataset exists only as the FALLBACK source — a
    tenant whose fetches never fail should not pay the data_fn call
    (often the single most expensive host step: synthetic generators,
    file parses) nor hold a dataset-sized array it never reads. All
    metadata (batch counts/sizes, shuffle identity, the epoch
    permutation replay — a pure function of (seed, n)) is available
    without materializing; the data-bearing accessors materialize
    lazily, and the realized arrays are validated against the declared
    ``num_examples``."""

    def __init__(
        self,
        arrays_fn,
        num_examples: int,
        num_mini_batches: int,
        shuffle_each_epoch: bool = False,
        seed: int = 0,
        dataset_key: "tuple | None" = None,
        array_specs: "list[tuple[tuple, Any]] | None" = None,
    ) -> None:
        if num_mini_batches <= 0 or num_mini_batches > num_examples:
            raise ValueError(
                f"bad num_mini_batches={num_mini_batches} for "
                f"n={num_examples}")
        self._arrays_fn = arrays_fn
        self._declared_n = int(num_examples)
        self.dataset_key = dataset_key if not shuffle_each_epoch else None
        self.batch_size = num_examples // num_mini_batches
        self.num_mini_batches = num_mini_batches
        self._shuffle = shuffle_each_epoch
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        self._arrays = None
        self._declared_specs = (
            None if array_specs is None
            else [(tuple(tail), np.dtype(dt)) for tail, dt in array_specs]
        )
        import threading

        self._replay = (0, np.random.default_rng(seed))
        self._replay_lock = threading.Lock()
        self._materialize_lock = threading.Lock()

    def array_specs(self):
        if self._arrays is None:
            if self._declared_specs is None:
                self._ensure()  # no declared specs: materialize to answer
            else:
                return list(self._declared_specs)
        return super().array_specs()

    def first_rows(self, k: int):
        self._ensure()
        return super().first_rows(k)

    def _ensure(self) -> None:
        with self._materialize_lock:
            if self._arrays is not None:
                return
            out = self._arrays_fn()
            arrays = [np.asarray(a)
                      for a in (out if isinstance(out, (tuple, list))
                                else (out,))]
            if not arrays or any(a.shape[0] != self._declared_n
                                 for a in arrays):
                raise ValueError(
                    "deferred provider materialized arrays that do not "
                    f"match the declared num_examples={self._declared_n}")
            self._arrays = [
                a[: self.batch_size * self.num_mini_batches]
                for a in arrays
            ]

    def epoch_batches(self):
        self._ensure()
        return super().epoch_batches()

    def epoch_batches_at(self, epoch: int):
        self._ensure()
        return super().epoch_batches_at(epoch)

    def batch_at(self, b: int):
        self._ensure()
        return super().batch_at(b)
