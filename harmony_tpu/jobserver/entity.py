"""JobEntity — app-type abstraction between the JobServer and frameworks.

Parity with the reference's JobEntity/JobMaster pair (jobserver/driver/
JobEntity.java, JobMaster.java): each app type implements table/executor
setup plus a run loop. DolphinJobEntity mirrors the reference's
(dolphin/jobserver/DolphinJobEntity.java:40-168): model table created on the
job's executors ("servers"), input provisioned to workers, PS-collocation
only (servers == workers == all granted executors), and input-table reuse
across jobs when the table id matches.

The trainer and its data come from the serializable JobConfig: dotted-path
symbols (config.base.resolve_symbol) stand in for Tang's
bind-implementation-by-class-name.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from harmony_tpu.config.base import resolve_symbol
from harmony_tpu.config.params import JobConfig, TrainerParams
from harmony_tpu.data.devcache import host_data as _HOST_DATA_CACHE
from harmony_tpu.dolphin.data import TrainingDataProvider
from harmony_tpu.dolphin.master import (
    BatchProgressTracker,
    MiniBatchController,
    WorkerStateManager,
)
from harmony_tpu.dolphin.trainer import TrainerContext
from harmony_tpu.dolphin.worker import WorkerTasklet
from harmony_tpu.metrics.collector import MetricCollector
from harmony_tpu.runtime.master import ETMaster, TableHandle
from harmony_tpu.runtime.taskunit import (
    GlobalTaskUnitScheduler,
    LocalTaskUnitScheduler,
    TaskUnitClient,
)


class JobEntity:
    """SPI: one instance per submitted job. ``chkp_root`` is where an app
    type may durably stage model checkpoints (unused by apps that have no
    model table to chain)."""

    def __init__(self, config: JobConfig, chkp_root: Optional[str] = None) -> None:
        self.config = config
        self.chkp_root = chkp_root

    def setup(self, master: ETMaster, executor_ids: List[str]) -> None:
        raise NotImplementedError

    def run(self) -> Dict[str, Any]:
        raise NotImplementedError

    def cleanup(self) -> None:
        raise NotImplementedError

    def deferred_evaluation(self):
        """Optional: return a closure(master) the JobServer should run at
        graceful shutdown (ref: deferred model evaluation,
        JobServerDriver.java:178-214). Default: nothing deferred."""
        return None


class DolphinJobEntity(JobEntity):
    def __init__(
        self,
        config: JobConfig,
        global_taskunit: Optional[GlobalTaskUnitScheduler] = None,
        local_taskunit: Optional[LocalTaskUnitScheduler] = None,
        metric_sink=None,
        chkp_root: Optional[str] = None,
        metric_manager=None,
        pod_plan_sink=None,
        pod_eval_channel=None,
        pod_unit_scope=None,
        pod_unit_contended=None,
    ) -> None:
        super().__init__(config, chkp_root)
        self._global_tu = global_taskunit
        self._local_tu = local_taskunit
        self._metric_sink = metric_sink
        self._metric_manager = metric_manager
        # Leader-side pod channels (present only on the pod leader for
        # single-dispatch-thread jobs): the plan channel lets the
        # optimizer loop run on multi-process grants; the eval channel
        # turns the shutdown-stage deferred model eval into a pod
        # collective (followers replay the same restores/evaluations in
        # lockstep).
        self._pod_plan_sink = pod_plan_sink
        self._pod_eval_channel = pod_eval_channel
        # Cross-job unit protocol (EVERY participating process of a
        # multi-process pod job — runtime/podunits.py): all of this job's
        # global-dispatch regions run inside leader-granted units so
        # overlapping tenants enqueue in one pod-wide order.
        self._pod_unit_scope = pod_unit_scope
        self._pod_unit_contended = pod_unit_contended
        self._chkp_mgr = None
        self._chkp_chain = None
        self._chkp_dir: Optional[str] = None
        self._master: Optional[ETMaster] = None
        self._handle: Optional[TableHandle] = None
        self._local_handle: Optional[TableHandle] = None
        self._workers: List[WorkerTasklet] = []
        self._ctrl: Optional[MiniBatchController] = None
        self.progress: Optional[BatchProgressTracker] = None
        self._applied_plans: List[Dict[str, Any]] = []  # pod reshard log
        # resume_from_chain: epoch to resume at + the restored chain's
        # global counter (so the continued chain keeps monotonic ids)
        self._starting_epoch = 0
        self._chkp_counter_base = 0
        #: elastic recovery accounting (restore stats + shrink plan) —
        #: set by _restore_elastic, surfaced in the job result
        self._elastic_restore: Optional[Dict[str, Any]] = None

    # -- setup -----------------------------------------------------------

    def _make_trainer(self):
        if not self.config.trainer:
            raise ValueError(f"job {self.config.job_id}: no trainer configured")
        cls = resolve_symbol(self.config.trainer)
        return cls(**self.config.params.app_params)

    def _data_source_key(self) -> "tuple | None":
        """Identity of this job's data source: the generator/loader dotted
        path + canonicalized args. Jobs sharing it reuse device-resident
        batches (data/devcache) — the analogue of the reference's same-id
        input-table reuse (DolphinJobEntity.java:76-121). None when args
        aren't canonicalizable (unhashable values)."""
        user = self.config.user

        def tag(v):
            # type-tagged recursively (see Trainer.jit_signature: True == 1
            # == 1.0 must not collide — a data_fn can behave differently per
            # type, and (1,) == (1.0,) collides the same way)
            if isinstance(v, (list, tuple)):
                return (type(v).__name__, tuple(tag(x) for x in v))
            return (type(v).__name__, v)

        try:
            args = tuple(sorted(
                (k, tag(v)) for k, v in user.get("data_args", {}).items()
            ))
            hash(args)
        except TypeError:
            return None
        return (user.get("data_fn"), args)

    def _make_data(self) -> List[np.ndarray]:
        """Materialize the job's dataset. Jobs with the SAME (data_fn,
        data_args) are defined to see the same dataset — the host arrays are
        cached under the source key (and the per-batch device copies under
        the same key in data/devcache), mirroring the reference's same-id
        input-table sharing. Non-deterministic sources that must differ per
        job should vary their args (e.g. a seed) to opt out."""
        user = self.config.user
        if "data_fn" not in user:
            raise ValueError(f"job {self.config.job_id}: user.data_fn missing")
        key = self._data_source_key()
        if key is not None:
            cached = _HOST_DATA_CACHE.get(key)
            if cached is not None:
                return cached
        fn = resolve_symbol(user["data_fn"])
        out = fn(**user.get("data_args", {}))
        arrays = [
            np.asarray(a)
            for a in (out if isinstance(out, (tuple, list)) else (out,))
        ]
        if key is not None:
            _HOST_DATA_CACHE.put(key, arrays)
        return arrays

    def _make_input_feed(self, provider, lo: int, hi: int, nb: int):
        """Input-service feed for one worker's slice — or None, which
        keeps in-process assembly. None whenever the job did not opt in
        (``TrainerParams.input_service`` / HARMONY_INPUT_SERVICE), the
        dataset identity cannot cross the wire, or no service endpoint
        is known (embedded service not running and no
        HARMONY_INPUT_SERVICE_ADDR) — the service is an optimization,
        never a dependency."""
        from harmony_tpu import inputsvc

        if not inputsvc.enabled_for(self.config.params):
            return None
        user = self.config.user
        if "data_fn" not in user:
            return None
        if inputsvc.default_endpoint() is None:
            return None
        try:
            spec = inputsvc.DatasetSpec.build(
                user["data_fn"], user.get("data_args", {}),
                lo=lo, hi=hi, num_mini_batches=nb,
                shuffle=provider.is_shuffling,
                seed=provider.seed,
            )
        except TypeError:
            return None  # non-canonical data_args: no wire identity
        return inputsvc.TrainerInputFeed(
            spec, provider, tenant=self.config.job_id,
        )

    def setup(self, master: ETMaster, executor_ids: List[str]) -> None:
        # Table creation dispatches multi-device init programs — under
        # cross-job pod tenancy that region must hold a dispatch unit like
        # any other (a concurrent tenant's enqueue interleaving with it
        # would diverge across processes).
        import contextlib

        scope = (self._pod_unit_scope() if self._pod_unit_scope is not None
                 else contextlib.nullcontext())
        with scope:
            self._setup_inner(master, executor_ids)

    def _setup_inner(self, master: ETMaster, executor_ids: List[str]) -> None:
        self._master = master
        cfg = self.config
        data_axis = max(1, cfg.user.get("data_axis", 1))
        probe = self._make_trainer()  # one probe serves all schema queries
        if cfg.tables:
            # Explicit table id => shared-table semantics: reuse if it exists
            # (the reference reuses same-id tables across jobs,
            # DolphinJobEntity.java:76-121 — deliberately shared state).
            self._handle, _ = master.get_or_create_table(
                cfg.tables[0], executor_ids, data_axis
            )
        elif cfg.user.get("elastic_recovery"):
            # Elastic in-place recovery (jobserver/elastic.py): the SAME
            # submission continues on a changed executor set — partial
            # restore reads only the blocks this process cannot source
            # from its recovery cache (O(lost bytes), the shrink
            # contract), at the epoch floor of the last committed chain
            # entry.
            if getattr(probe, "uses_local_table", False):
                raise ValueError(
                    f"job {cfg.job_id}: elastic recovery does not cover "
                    "worker-local tables (their state is not chained)"
                )
            self._handle, self._starting_epoch, self._chkp_counter_base = (
                self._restore_elastic(master, executor_ids, data_axis)
            )
        elif cfg.user.get("resume_from_chain"):
            # Auto-resume: rebuild the model table from the job's LAST
            # committed chain checkpoint (restore-by-state, ref:
            # ETMaster.createTable(chkpId, associators)) and continue from
            # the epoch it covers. The restore is cross-topology, so the
            # grant may be a different executor set than the one that
            # wrote the chain (a shrunk pod after a follower death).
            if getattr(probe, "uses_local_table", False):
                raise ValueError(
                    f"job {cfg.job_id}: resume_from_chain does not cover "
                    "worker-local tables (their state is not chained)"
                )
            self._handle, self._starting_epoch, self._chkp_counter_base = (
                self._restore_chain(master, executor_ids, data_axis)
            )
        else:
            # Trainer-default schema => PRIVATE model table: namespace by job
            # id so two concurrent jobs of the same app never collide on the
            # trainer's fixed default id (e.g. two MLR jobs both saying
            # "mlr-model").
            table_cfg = probe.model_table_config()
            table_cfg = table_cfg.replace(
                table_id=f"{cfg.job_id}:{table_cfg.table_id}"
            )
            self._handle = master.create_table(table_cfg, executor_ids, data_axis)
        self._trainer_factory = lambda: (
            resolve_symbol(cfg.trainer)(**cfg.params.app_params)
        )
        # Worker-local model table (ref: DolphinJobEntity's optional
        # local-model table, created on workers alongside the input table).
        self._local_handle = None
        if getattr(probe, "uses_local_table", False):
            local_cfg = probe.local_table_config()
            local_cfg = local_cfg.replace(table_id=f"{cfg.job_id}:{local_cfg.table_id}")
            self._local_handle = master.create_table(local_cfg, executor_ids, data_axis)
        self._executor_ids = list(executor_ids)
        self._data_arrays = self._make_data()

    # -- run (the DolphinMaster.start analogue) --------------------------

    def _restore_chain(self, master: ETMaster, executor_ids: List[str],
                       data_axis: int):
        """Rebuild the model table from the MOST RECENTLY WRITTEN chain
        checkpoint (by the monotonic epoch tag; created_at tie-breaks —
        id counters are NOT a reliable epoch clock: the pod id scan skips
        past a stale run's ids, and a resubmitted single-process chain
        restarts its counter) and resume at the EPOCH the manifest
        records (chain entries carry app_meta={"epoch": e}; the snapshot
        covers epoch e, so training resumes at e+1).

        Exactness: single-worker resume is numerically identical to an
        uninterrupted run (the snapshot is a clean epoch cut). For
        multi-worker SSP jobs the snapshot is a CONSISTENT table state at
        the chief's hook slot that may already contain sibling pushes
        from their in-flight epoch; resuming replays those — approximate,
        exactly like the reference's StartingEpochIdx resume (workers
        restart from global MIN progress and re-apply beyond it), and
        acceptable under bounded-staleness semantics.

        Returns (handle, starting_epoch, counter_base)."""
        mgr, ordered, base = self._chain_scan("resume_from_chain")
        cfg = self.config
        from harmony_tpu.checkpoint.manager import CheckpointCorruptError
        from harmony_tpu.jobserver.joblog import job_logger

        failures = []
        for info in ordered:
            try:
                handle = mgr.restore(master, info.chkp_id, executor_ids,
                                     data_axis)
            except (CheckpointCorruptError, FileNotFoundError) as e:
                job_logger(cfg.job_id).warning(
                    "chain entry %s is corrupt/torn (%s: %s); quarantining "
                    "and falling back to the previous committed entry",
                    info.chkp_id, type(e).__name__, e,
                )
                failures.append((info.chkp_id, f"{type(e).__name__}: {e}"))
                mgr.quarantine(info.chkp_id)
                continue
            return handle, int(info.app_meta["epoch"]) + 1, base
        raise ValueError(
            f"job {cfg.job_id}: every chain checkpoint failed integrity "
            f"on restore (all quarantined): {failures}"
        )

    def _chain_scan(self, why: str):
        """Shared chain discovery for resume_from_chain AND elastic
        recovery: epoch-tagged entries under this job's chkp root,
        newest-first by the MONOTONIC epoch tag (wall clock can regress
        across hosts/NTP steps and must never discard newer progress;
        created_at only tie-breaks entries claiming the same epoch —
        a resubmitted-from-scratch chain re-covering old ones), plus the
        continuation counter base (ids stay unique/ordered past EVERY
        existing entry; the epoch clock is the manifest tag, never the
        counter). Torn-manifest entries are quarantined during the scan.
        Returns (manager, ordered_infos, counter_base)."""
        from harmony_tpu.checkpoint.manager import (
            CheckpointCorruptError,
            CheckpointManager,
        )
        from harmony_tpu.jobserver.joblog import job_logger

        cfg = self.config
        if self.chkp_root is None:
            raise ValueError(
                f"job {cfg.job_id}: {why} needs the server's chkp_root "
                "(the chain lives there)"
            )
        mgr = CheckpointManager.for_job(self.chkp_root, cfg.job_id)
        prefix = f"{cfg.job_id}:"
        infos = []
        for cid in mgr.list_checkpoints():
            if not cid.startswith(prefix):
                continue
            try:
                info = mgr.info(cid)
            except CheckpointCorruptError as e:
                # torn manifest: this entry can never restore — quarantine
                # it NOW so no later scan trips on it either
                job_logger(cfg.job_id).warning(
                    "chain entry %s has a torn manifest (%s); quarantined",
                    cid, e,
                )
                mgr.quarantine(cid)
                continue
            if info.app_meta is None or "epoch" not in info.app_meta:
                continue  # not a chain entry (no epoch tag)
            infos.append(info)
        if not infos:
            raise ValueError(
                f"job {cfg.job_id}: {why} found no epoch-tagged chain "
                f"checkpoints under {self.chkp_root}"
            )

        def counter_of(cid: str) -> int:
            try:
                return int(cid.rsplit("-", 2)[1])
            except (ValueError, IndexError):
                return 0

        base = max(counter_of(i.chkp_id) for i in infos)
        # Newest-first with CORRUPTION FALLBACK (callers quarantine a
        # failing entry and try the previous committed one — losing one
        # epoch of progress beats failing the resume outright; anything
        # non-corruption aborts immediately: it would fail identically
        # on every entry).
        ordered = sorted(
            infos,
            key=lambda i: (int(i.app_meta["epoch"]), i.created_at),
            reverse=True,
        )
        return mgr, ordered, base

    def _restore_elastic(self, master: ETMaster, executor_ids: List[str],
                         data_axis: int):
        """The shrink/re-grow restore: newest committed chain entry,
        partial-read (recovery cache first, checkpoint storage only for
        what this process genuinely lost — manager.restore_partial), with
        the same newest->oldest corruption fallback as _restore_chain.
        Records the restore accounting (the O(lost-bytes) evidence) in
        ``self._elastic_restore`` for the job result. Returns
        (handle, starting_epoch, counter_base)."""
        from harmony_tpu import faults
        from harmony_tpu.checkpoint.manager import CheckpointCorruptError
        from harmony_tpu.jobserver.joblog import job_logger
        from harmony_tpu.table import ownership as _ownership

        cfg = self.config
        rec = cfg.user.get("elastic_recovery") or {}
        mgr, ordered, base = self._chain_scan("elastic recovery")
        failures = []
        for info in ordered:
            if faults.armed():
                faults.site("elastic.restore", chkp_id=info.chkp_id,
                            attempt=int(rec.get("attempt", 0)))
            try:
                handle, stats = mgr.restore_partial(
                    master, info.chkp_id, executor_ids, data_axis
                )
            except (CheckpointCorruptError, FileNotFoundError) as e:
                job_logger(cfg.job_id).warning(
                    "elastic recovery: chain entry %s is corrupt/torn "
                    "(%s: %s); quarantining and falling back",
                    info.chkp_id, type(e).__name__, e,
                )
                failures.append((info.chkp_id, f"{type(e).__name__}: {e}"))
                mgr.quarantine(info.chkp_id)
                continue
            lost_execs = [e for e in rec.get("lost_executors", [])
                          if e in info.executors]
            plan = None
            if lost_execs:
                try:
                    plan = _ownership.shrink_plan(
                        info.ownership, info.executors, lost_execs,
                        executor_ids,
                    )
                except ValueError:
                    plan = None
            self._elastic_restore = {
                "attempt": int(rec.get("attempt", 0)),
                "kind": rec.get("kind", "shrink"),
                "chkp_id": info.chkp_id,
                "resumed_epoch": int(info.app_meta["epoch"]) + 1,
                "executors": list(executor_ids),
                "lost_executors": list(lost_execs),
                "lost_block_count": (len(plan["lost"]) if plan else 0),
                **stats,
            }
            job_logger(cfg.job_id).event(
                "elastic_restore",
                recovery=self._elastic_restore["kind"],
                **{k: v for k, v in self._elastic_restore.items()
                   if k not in ("executors", "kind")})
            return handle, int(info.app_meta["epoch"]) + 1, base
        raise ValueError(
            f"job {cfg.job_id}: every chain checkpoint failed integrity "
            f"during elastic recovery (all quarantined): {failures}"
        )

    def run(self) -> Dict[str, Any]:
        cfg = self.config
        params: TrainerParams = cfg.params
        # num_workers == 0 means "one worker per granted executor" (the
        # documented 'all executors' default, ref SchedulerImpl runs on all).
        num_workers = cfg.num_workers or len(self._executor_ids)
        nb = params.num_mini_batches
        from harmony_tpu.jobserver.joblog import job_logger

        job_logger(cfg.job_id).info(
            "training: %d worker(s), %d epoch(s) x %d mini-batch(es)",
            num_workers, params.num_epochs, nb,
        )
        # floor_batch: a RESUMED continuation (auto-resume / elastic
        # recovery) must never report an epoch floor below its resume
        # point — the pod plan/fence horizon check reads this
        self.progress = BatchProgressTracker(
            nb, floor_batch=self._starting_epoch * nb
        )
        # Model-checkpoint chaining (ref: ModelChkpManager wired by
        # DolphinMaster.start:186-189): snapshots run off the CHIEF worker's
        # epoch hook — one snapshot per job epoch, async writers.
        epoch_hook = None
        if params.model_chkp_period > 0:
            from harmony_tpu.parallel.mesh import mesh_spans_processes

            spans = mesh_spans_processes(self._handle.table.mesh)
            if spans:
                # Pod checkpoint chains ride the synchronous collective
                # path (ModelChkpManager.on_epoch -> CheckpointManager
                # pod branch). Legal for ANY worker count: the epoch hook
                # runs INSIDE the chief's turnstile turn (_finish_epoch),
                # the same deterministic cycle slot on every process —
                # the same argument that admits pod reshard plans. Needs
                # a SHARED chkp root (each process stages its own blocks
                # into one checkpoint directory).
                if self.chkp_root is None:
                    raise ValueError(
                        f"job {cfg.job_id}: pod checkpoint chains need a "
                        "SHARED chkp_root (per-process temp dirs would "
                        "each hold only a fragment of every checkpoint)"
                    )
                if params.offline_model_eval:
                    # Guards must be SYMMETRIC across processes — one
                    # process raising while its peers proceed into the
                    # job's collectives wedges the pod. Every process can
                    # evaluate the structural support condition itself:
                    # the grant must include the pod leader (process 0 —
                    # the only holder of the eval channel). Followers of
                    # a supported grant legitimately lack the channel
                    # (they replay on the EVAL_COLLECTIVE broadcast).
                    import jax as _jax

                    procs = {
                        d.process_index
                        for d in self._handle.table.mesh.devices.flat
                    }
                    if 0 not in procs:
                        raise ValueError(
                            f"job {cfg.job_id}: offline_model_eval needs "
                            "the grant to include the pod leader "
                            "(process 0), which runs the collective eval"
                        )
                    if (_jax.process_index() == 0
                            and self._pod_eval_channel is None):
                        raise ValueError(
                            f"job {cfg.job_id}: offline_model_eval on a "
                            "multi-process grant needs the pod eval "
                            "channel (running outside a PodJobServer?)"
                        )
            import os
            import tempfile

            from harmony_tpu.checkpoint.manager import CheckpointManager
            from harmony_tpu.dolphin.evaluator import ModelChkpManager

            root = self.chkp_root or tempfile.mkdtemp(
                prefix=f"harmony-chkp-{cfg.job_id}-"
            )
            self._chkp_dir = root
            self._chkp_mgr = CheckpointManager.for_job(root, cfg.job_id)
            if cfg.user.get("elastic_shrink"):
                # elastic jobs keep a host copy of THIS process's staged
                # blocks per chain entry (the recovery cache): a shrink
                # restore then reads only genuinely lost blocks from
                # storage — the O(lost-bytes) contract
                from harmony_tpu.jobserver import elastic as _elastic

                self._chkp_mgr.recovery_retain = _elastic.cache_enabled()
            if self._chkp_counter_base:
                # a RESUMED job continues its chain: counters (and the
                # epoch mapping a future resume derives from them) stay
                # monotonic across the restart
                self._chkp_mgr.advance_counter(self._chkp_counter_base)
            self._chkp_chain = ModelChkpManager(
                self._chkp_mgr, self._handle, period=params.model_chkp_period
            )
            epoch_hook = self._chkp_chain.on_epoch
        tm_hook = self._make_table_metrics_hook()
        # Single-worker jobs have no MiniBatchController to feed the
        # progress tracker; feed it from the epoch hook so the pod plan
        # horizon check (schedule_pod_reshard) has a REAL observed floor
        # instead of a vacuous 0. Deferrable (host accounting only): under
        # multi-epoch windows the replay feeds it post-drain in order, so
        # the floor lags at most one window — conservative, never ahead.
        tracker_hook = None
        if num_workers == 1:
            _tracker, _wid0 = self.progress, f"{cfg.job_id}/w0"

            def tracker_hook(e: int) -> None:
                _tracker.on_batch(_wid0, (e + 1) * nb - 1)

        epoch_hook = self._compose_epoch_hooks(
            tracker_hook, epoch_hook, tm_hook, self._make_pod_plan_hook()
        )
        from harmony_tpu.jobserver import podplan

        plan_epoch_fn = (lambda: podplan.next_epoch(cfg.job_id))
        orchestrator = self._make_orchestrator()
        # Pod lockstep: a multi-worker job whose grant spans host processes
        # needs a deterministic dispatch schedule — every process runs the
        # same worker threads, and their global SPMD programs must enqueue
        # in the same order everywhere (dolphin/master.DispatchTurnstile).
        # The SSP slack is clamped to >=1 so the gate never blocks INSIDE a
        # turn (turnstile divergence is bounded by one turn anyway, which
        # is stricter than any slack); TaskUnit announcement is dropped —
        # the pod admission rule gives multi-process jobs exclusive
        # processes, so there are no tenants to interleave with.
        # user.force_lockstep opts a single-process job into the same
        # deterministic schedule — the reproducible-baseline switch pod
        # tests compare against (same schedule => identical numerics).
        # NOTE: lockstep jobs drop TaskUnit admission (a quorum wait
        # inside a turn deadlocks the cycle); on a pod the admission rule
        # gives multi-process jobs exclusive processes so nothing is lost,
        # but a force_lockstep job on a SHARED single-process server opts
        # out of the 1-CPU/2-NET interleaving contract with co-tenants —
        # it is a determinism knob, not a production scheduling mode.
        pod_lockstep = num_workers > 1 and (
            len({
                self._master.executor(e).device.process_index
                for e in self._executor_ids
            }) > 1
            or bool(cfg.user.get("force_lockstep"))
        )
        turnstile = None
        if pod_lockstep:
            from harmony_tpu.dolphin.master import DispatchTurnstile

            turnstile = DispatchTurnstile(
                [f"{cfg.job_id}/w{i}" for i in range(num_workers)]
            )
        self._ctrl = (
            MiniBatchController(
                max(params.clock_slack, 1) if pod_lockstep
                else params.clock_slack,
                params.num_epochs * nb,
                tracker=self.progress,
            )
            if num_workers > 1
            else None
        )
        wsm = WorkerStateManager([f"{cfg.job_id}/w{i}" for i in range(num_workers)])
        # Chief-only global init: others wait here until it has run
        # (see WorkerTasklet.global_init).
        init_barrier = threading.Barrier(num_workers)
        if self._global_tu is not None:
            self._global_tu.on_job_start(
                cfg.job_id, [f"{cfg.job_id}/w{i}" for i in range(num_workers)]
            )
        n = self._data_arrays[0].shape[0]
        if n < num_workers * nb:
            raise ValueError(
                f"job {cfg.job_id}: {n} examples cannot feed {num_workers} "
                f"workers x {nb} mini-batches"
            )
        per = n // num_workers
        results: Dict[str, Any] = {}
        errors: List[BaseException] = []
        # Trace threading: worker threads cannot inherit the dispatch
        # span's contextvar, so capture its wire context HERE (the
        # dispatch thread) and hand it down; the elastic attempt index
        # labels every worker span/histogram with the job@aN key.
        from harmony_tpu.jobserver import elastic as _elastic
        from harmony_tpu.tracing.span import wire_context

        trace_parent = wire_context()
        attempt = _elastic.attempt_of(cfg)

        def run_worker(idx: int) -> None:
            wid = f"{cfg.job_id}/w{idx}"
            try:
                wsm.await_barrier(wid, "INIT")
                # Last worker takes the remainder so no example is dropped.
                hi = (idx + 1) * per if idx < num_workers - 1 else n
                sl = slice(idx * per, hi)
                src = self._data_source_key()
                data = TrainingDataProvider(
                    [a[sl] for a in self._data_arrays], nb,
                    dataset_key=(
                        None if src is None else (src, sl.start, hi, nb)
                    ),
                )
                input_feed = self._make_input_feed(data, sl.start, hi, nb)
                ctx = TrainerContext(
                    params=params,
                    model_table=self._handle.table,
                    local_table=(
                        self._local_handle.table
                        if self._local_handle is not None
                        else None
                    ),
                    worker_id=wid,
                    num_workers=num_workers,
                )
                # Pod-unit jobs drop local TaskUnit admission: ordering
                # AND cross-tenant fairness come from the pod arbiter (a
                # local quorum wait inside a granted unit would deadlock
                # the grant discipline the same way it would a turnstile
                # turn).
                taskunit = (
                    TaskUnitClient(cfg.job_id, wid, self._global_tu, self._local_tu)
                    if self._global_tu is not None
                    and self._local_tu is not None
                    and not pod_lockstep
                    and self._pod_unit_scope is None
                    else None
                )
                worker = WorkerTasklet(
                    cfg.job_id,
                    ctx,
                    self._trainer_factory(),
                    data,
                    self._handle.table.mesh,
                    collector=MetricCollector(sink=self._metric_sink,
                                              job_id=cfg.job_id,
                                              worker_id=wid),
                    batch_barrier=(
                        self._ctrl.make_barrier(wid) if self._ctrl is not None else None
                    ),
                    taskunit=taskunit,
                    epoch_callback=(epoch_hook if idx == 0 else None),
                    starting_epoch=self._starting_epoch,
                    # resumed jobs must NOT re-run global init: the
                    # restored table already holds trained state, and an
                    # additive init would corrupt it
                    global_init=(idx == 0 and self._starting_epoch == 0),
                    post_init_barrier=init_barrier.wait,
                    dispatch_turn=self._make_dispatch_turn(turnstile, wid),
                    pod_contended=self._pod_unit_contended,
                    pending_plan_epoch=(plan_epoch_fn if idx == 0 else None),
                    # the metrics hook only reads already-drained counters,
                    # so fused multi-epoch windows may defer it; checkpoint
                    # chains snapshot state AT their epoch and disable them
                    defer_epoch_callback=(params.model_chkp_period <= 0),
                    trace_parent=trace_parent,
                    attempt=attempt,
                    input_feed=input_feed,
                )
                self._workers.append(worker)
                results[wid] = worker.run()
            except BaseException as e:  # noqa: BLE001 - reported to dispatcher
                errors.append(e)
                # A worker that dies before the init barrier must break it,
                # or every other worker waits forever (fail-fast, like the
                # reference's driver-kill on evaluator failure).
                init_barrier.abort()
            finally:
                if turnstile is not None:
                    # a finished (or dead) worker must not stall the cycle
                    turnstile.leave(wid)
                if self._ctrl is not None:
                    self._ctrl.deregister_worker(wid)
                if self._global_tu is not None:
                    # Shrink the TaskUnit quorum, or surviving workers
                    # deadlock waiting for this one's phase announcements.
                    self._global_tu.on_executor_done(cfg.job_id, wid)
                wsm.await_barrier(wid, "CLEANUP", timeout=60)

        threads = [
            threading.Thread(target=run_worker, args=(i,), name=f"{cfg.job_id}-w{i}")
            for i in range(num_workers)
        ]
        if orchestrator is not None:
            orchestrator.start()
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            if orchestrator is not None:
                orchestrator.stop()
                self._master.release_optimizer_lease(self._handle.table_id)
        if self._global_tu is not None:
            self._global_tu.on_job_finish(cfg.job_id)
        if errors:
            fence = next(
                (e for e in errors
                 if getattr(e, "elastic_fence", None) is not None), None,
            )
            if fence is not None and self._chkp_chain is not None:
                # an elastic fence ends the attempt ON PURPOSE right
                # after the fence epoch's chain hook — join the async
                # writers so the recovery point is COMMITTED before the
                # leader plans the next attempt (otherwise the restore
                # falls back an epoch and re-runs it)
                try:
                    self._chkp_chain.drain()
                except BaseException:  # noqa: BLE001 - fence still stands
                    pass
            if fence is not None:
                # the fence outranks sibling errors: a worker released by
                # the fence's stop broadcast may error while unwinding,
                # and raising THAT would strip the marker the elastic
                # loop classifies on — permanently failing a submission
                # that was mid-planned-reconfiguration
                raise fence
            raise errors[0]
        if tm_hook is not None:
            # final report AFTER all workers joined: the chief's last epoch
            # hook fires while SSP-lagging peers may still be dispatching;
            # their tail ops land in this closing window
            tm_hook(params.num_epochs)
        out: Dict[str, Any] = {"job_id": cfg.job_id, "workers": results}
        if self._elastic_restore is not None:
            # the recovery attempt's restore accounting (the O(lost-bytes)
            # evidence the elastic chaos tests assert against)
            out["elastic_restore"] = dict(self._elastic_restore)
        if self._applied_plans:
            out["applied_plans"] = list(self._applied_plans)
        if orchestrator is not None:
            out["reconfigs"] = len(orchestrator.reconfig_log)
            if orchestrator.errors:
                # failed rounds must be visible in the job result, not just
                # in a list that dies with the orchestrator
                out["optimizer_errors"] = [
                    f"{type(e).__name__}: {e}" for e in orchestrator.errors
                ]
        if self._chkp_chain is not None:
            # Join the async snapshot writers before the dispatcher drops the
            # table; the surviving ids are the replayable chain. A checkpoint
            # problem must NOT fail a job whose training succeeded — record
            # it as a warning and return the ids still considered live.
            try:
                out["model_chkp_ids"] = self._chkp_chain.drain()
            except BaseException as e:  # noqa: BLE001 - demoted to warning
                out["model_chkp_ids"] = list(self._chkp_chain.chkp_ids)
                out["model_chkp_warning"] = f"{type(e).__name__}: {e}"
            # The chain is a durable artifact (like the reference's
            # HDFS-committed checkpoints): surface where it lives so callers
            # can replay or delete it.
            out["model_chkp_root"] = self._chkp_dir
        return out

    def _make_dispatch_turn(self, turnstile, wid: str):
        """The worker's per-dispatch admission context: the job-internal
        turnstile turn (multi-worker determinism), the cross-job pod unit
        (share-all ordering), their COMPOSITION (turn outside, unit
        inside — the turnstile serializes this process's threads so unit
        sequence numbers stay deterministic), or None (single-process
        single-thread jobs need neither)."""
        import contextlib

        scope = self._pod_unit_scope
        if turnstile is None and scope is None:
            return None
        if turnstile is None:
            return scope
        if scope is None:
            return lambda: turnstile.turn(wid)

        @contextlib.contextmanager
        def composed():
            with turnstile.turn(wid):
                with scope():
                    yield

        return composed

    _OPTIMIZERS = {
        "homogeneous": "harmony_tpu.optimizer:HomogeneousOptimizer",
        "heterogeneous": "harmony_tpu.optimizer:HeterogeneousOptimizer",
        "add_one_server": "harmony_tpu.optimizer:AddOneServerOptimizer",
        "delete_one_server": "harmony_tpu.optimizer:DeleteOneServerOptimizer",
        "empty": "harmony_tpu.optimizer:EmptyPlanOptimizer",
    }

    def _make_orchestrator(self):
        """Per-job elasticity loop (ref: ETOptimizationOrchestrator run by
        the driver for each Dolphin job): metrics -> Optimizer -> plan ->
        live migration of THIS job's model table while it trains. Enabled
        by JobConfig.optimizer (a registry name or dotted path)."""
        name = self.config.optimizer
        if not name:
            return None
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        plan_sink = None
        if mesh_spans_processes(self._handle.table.mesh):
            # Multi-process grant: ONLY the leader runs the optimization
            # loop, and its plans are HANDED to the pod control plane for
            # epoch-aligned lockstep application (followers return None —
            # they apply plans, never produce them). Rejections here must
            # be SYMMETRIC across processes (one process raising while its
            # peers proceed into the job's collectives wedges the pod), so
            # the support condition is derived purely from config + mesh:
            # the grant must include the pod leader (process 0), the only
            # holder of the plan channel. Every participant evaluates the
            # same predicate and raises together.
            import jax as _jax

            procs = {
                d.process_index
                for d in self._handle.table.mesh.devices.flat
            }
            if 0 not in procs:
                raise ValueError(
                    f"job {self.config.job_id}: optimizer={name!r} on a "
                    "multi-process grant needs the grant to include the "
                    "pod leader (process 0), which runs the optimization "
                    "loop and owns the plan channel"
                )
            if _jax.process_index() != 0:
                return None
            if self._pod_plan_sink is None:
                # Only reachable OUTSIDE a PodJobServer (which wires the
                # sink for every multi-process grant): there are no pod
                # followers to desynchronize from in that case, so a
                # one-sided raise is safe.
                raise ValueError(
                    f"job {self.config.job_id}: optimizer={name!r} on a "
                    "multi-process grant has no pod plan channel "
                    "(running outside a PodJobServer?)"
                )
            plan_sink = self._make_pod_plan_adapter()
        if self._metric_manager is None:
            raise ValueError(
                f"job {self.config.job_id}: optimizer={name!r} needs the "
                "jobserver's MetricManager (running outside a JobServer?)"
            )
        # One optimization loop per table: a tenant attaching to a shared
        # table whose creator already optimizes it trains unoptimized
        # rather than racing competing migration plans.
        if not self._master.acquire_optimizer_lease(self._handle.table_id):
            return None
        try:
            from harmony_tpu.optimizer import OptimizationOrchestrator

            cls = resolve_symbol(self._OPTIMIZERS.get(name, name))
            return OptimizationOrchestrator(
                self._master,
                self._handle,
                cls(),
                self._metric_manager,
                period_sec=self.config.optimizer_period,
                job_id=self.config.job_id,
                plan_sink=plan_sink,
            )
        except BaseException:
            # run()'s finally only releases through the orchestrator; a
            # construction failure here would otherwise hold the lease
            # forever and make every resubmission train unoptimized
            self._master.release_optimizer_lease(self._handle.table_id)
            raise

    def _make_pod_plan_adapter(self):
        """Adapt a DolphinPlan to the pod plan channel: move-only plans
        (the pod's reconfiguration unit) are scheduled at the earliest
        epoch clearing the window-horizon lead past this leader's observed
        progress; executor add/delete plans are declined (pod topology
        changes are a process-lifecycle operation, not a table move)."""
        from harmony_tpu.dolphin.worker import WorkerTasklet

        job_id = self.config.job_id
        sink = self._pod_plan_sink
        metrics = self._metric_manager
        # Monotonic high-water mark of observed epochs: run_once clears
        # job metrics after an accepted plan, and a later round reading
        # EMPTY metrics must not regress its epoch estimate to 0 and
        # schedule a plan BEHIND the job's real progress (the divergent-
        # application hazard; the pod-side progress-tracker check is
        # vacuous for single-worker jobs).
        seen = {"hi": 0}

        def apply(dplan) -> bool:
            if dplan.evaluators_to_add or dplan.evaluators_to_delete:
                from harmony_tpu.jobserver.joblog import job_logger

                job_logger(job_id).warning(
                    "pod optimization declined a plan with executor "
                    "add/delete (move-only plans are supported on pods)"
                )
                return False
            wm = metrics.worker_batch_metrics(job_id=job_id)
            cur = max((m.epoch_idx for m in wm), default=0)
            cur = seen["hi"] = max(cur, seen["hi"])
            epoch = cur + WorkerTasklet.EPOCH_WINDOW + 2
            if epoch >= self.config.params.num_epochs:
                from harmony_tpu.jobserver.joblog import job_logger

                job_logger(job_id).warning(
                    "pod optimization declined: earliest safe apply epoch "
                    "%d is past the job's end (%d epochs) — too few "
                    "epochs remain for a lockstep migration",
                    epoch, self.config.params.num_epochs,
                )
                return False
            for step in dplan.transfer_steps:
                sink(job_id, step.src, step.dst, step.num_blocks, epoch)
            return bool(dplan.transfer_steps)

        return apply

    def _make_pod_plan_hook(self):
        """Apply pod-scheduled reshard plans at the chief's epoch hook —
        the deterministic lockstep point every process reaches at the same
        logical epoch (see jobserver/podplan.py and
        PodJobServer.schedule_pod_reshard). Deferrable: under multi-epoch
        windows the hook replays post-drain in epoch order, identically on
        every process, so the move still lands at one consistent point.
        Single-process servers never schedule plans; the hook is a dict
        lookup per epoch there."""
        from harmony_tpu.jobserver import podplan

        job_id = self.config.job_id

        def hook(epoch_idx: int) -> None:
            for p in podplan.take(job_id, epoch_idx):
                if p.get("elastic_fence"):
                    # Elastic fence: this attempt ends HERE — at the one
                    # point lockstep guarantees every process reaches at
                    # the same logical epoch, right AFTER the chain hook
                    # snapshotted this epoch (hook composition order in
                    # run()), so the re-dispatch resumes at epoch+1 with
                    # nothing lost. Sibling workers are released through
                    # the SSP stop broadcast; the fence error carries the
                    # marker the elastic dispatch loop classifies on.
                    from harmony_tpu.jobserver.elastic import ElasticFence

                    if self._ctrl is not None:
                        self._ctrl.request_stop()
                    raise ElasticFence(str(p["elastic_fence"]), epoch_idx)
                # clamp to what src actually owns (deterministic: every
                # process sees the same block map) so "drain" plans can
                # just pass a large count
                counts = self._handle.block_manager.block_counts()
                owned = counts.get(p["src"], 0)
                n = min(int(p["num_blocks"]), owned)
                skipped = None
                if n:
                    # Process-set guard: a plan that would change WHICH
                    # PROCESSES own blocks mid-training is skipped (every
                    # process computes the same decision from the shared
                    # block map). A worker whose process left the table
                    # mesh would keep dispatching programs over devices
                    # it no longer shares — on multi-controller runtimes
                    # that wedges collective-context setup. Executor-level
                    # moves (including cross-process grows while the
                    # process still owns other blocks) are unrestricted;
                    # table-level process grow/shrink outside a training
                    # loop is fully supported (cross_set_reshard).
                    def owner_procs(cmap):
                        return {
                            self._master.executor(e).device.process_index
                            for e, c in cmap.items() if c > 0
                        }

                    after = dict(counts)
                    after[p["src"]] = owned - n
                    after[p["dst"]] = after.get(p["dst"], 0) + n
                    if owner_procs(after) != owner_procs(counts):
                        from harmony_tpu.jobserver.joblog import job_logger

                        skipped = "process-set change mid-training"
                        job_logger(job_id).warning(
                            "pod plan %s->%s (%d blocks) skipped: it "
                            "would change the owning PROCESS set of a "
                            "running job", p["src"], p["dst"], n,
                        )
                        n = 0
                if n:
                    self._handle.move_blocks(p["src"], p["dst"], n)
                entry = {
                    "epoch": epoch_idx, "src": p["src"], "dst": p["dst"],
                    "moved": n,
                    "owners_after": len(self._handle.owning_executors()),
                }
                if skipped:
                    entry["skipped"] = skipped
                self._applied_plans.append(entry)

        return hook

    def cleanup(self) -> None:
        """Table teardown (_cleanup_tables) + drop any unapplied pod
        reshard plans (a resubmitted job id must not inherit them)."""
        from harmony_tpu.jobserver import podplan

        podplan.clear(self.config.job_id)
        self._cleanup_tables()

    @staticmethod
    def _compose_epoch_hooks(*hooks):
        hooks = [h for h in hooks if h is not None]
        if not hooks:
            return None
        if len(hooks) == 1:
            return hooks[0]

        def composed(epoch_idx: int) -> None:
            for h in hooks:
                h(epoch_idx)

        return composed

    def _make_table_metrics_hook(self):
        """Per-epoch ServerMetrics emission (ref: the ET MetricReportMsg
        built-ins every executor reports — per-table block counts, pull
        request counts, pulled bytes — feeding MetricManager and through it
        the optimizer's cost models). Single-controller attribution: each
        owning executor reports its block count and a block-proportional
        share of THIS JOB's op-counter deltas since the last report — the
        deltas come from the job's own workers, not the table's cumulative
        counters, so jobs sharing one table never claim each other's
        traffic."""
        if self._metric_sink is None:
            return None
        from harmony_tpu.metrics.collector import ServerMetrics

        last = {"pulls": 0, "pushes": 0, "pull_bytes": 0}
        job_id = self.config.job_id
        handle = self._handle

        # largest-remainder split: shares sum EXACTLY to the total (plain
        # flooring leaks remainder ops every window)
        from harmony_tpu.optimizer.hetero import _largest_remainder as apportion

        def report(epoch_idx: int) -> None:
            stats = {k: 0 for k in last}
            for w in list(self._workers):
                for k in stats:
                    stats[k] += w.op_stats[k]
            delta = {k: stats[k] - last[k] for k in last}
            last.update(stats)
            counts = handle.block_manager.block_counts()
            owners = [(ex, n) for ex, n in counts.items() if n > 0]
            weights = [n for _, n in owners]
            pulls = apportion(delta["pulls"], weights)
            pushes = apportion(delta["pushes"], weights)
            pbytes = apportion(delta["pull_bytes"], weights)
            for i, (ex, nblocks) in enumerate(owners):
                self._metric_sink(ServerMetrics(
                    job_id=job_id,
                    executor_id=ex,
                    window_idx=epoch_idx,
                    num_blocks=nblocks,
                    pull_count=pulls[i],
                    push_count=pushes[i],
                    pull_bytes=pbytes[i],
                ))

        return report

    def deferred_evaluation(self):
        """Return a closure replaying this job's checkpoint chain, or None.

        Registered with the JobServer after a successful run; executed during
        graceful shutdown (ref: JobServerDriver.java:178-214 — shutdown waits
        for jobs, then runs the deferred model evaluation that
        DolphinMaster.evaluate() performs over the ModelChkpManager chain).
        Test data resolves lazily inside the closure (user.test_data_fn,
        falling back to the training data) so nothing large is pinned between
        job end and shutdown. Replayed checkpoints are deleted after
        evaluation — the eval is the chain's consumer — so a long-lived
        server doesn't accrete one model copy per epoch per job."""
        if self._chkp_chain is None or not self.config.params.offline_model_eval:
            return None
        chkp_ids = list(self._chkp_chain.chkp_ids)
        if not chkp_ids:
            return None
        cfg = self.config
        mgr = self._chkp_mgr
        executor_ids = list(self._executor_ids)
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        eval_channel = (
            self._pod_eval_channel
            if mesh_spans_processes(self._handle.table.mesh)
            else None
        )

        def run_eval(master: ETMaster) -> List[Dict[str, float]]:
            from harmony_tpu.dolphin.evaluator import (
                ModelEvaluator,
                resolve_eval_inputs,
            )

            # the SHARED resolution (leader and pod followers must issue
            # byte-identical collectives — see resolve_eval_inputs)
            trainer, batch = resolve_eval_inputs(cfg)
            if eval_channel is None:
                metrics = ModelEvaluator(master, mgr).evaluate_checkpoints(
                    chkp_ids, trainer, batch, executor_ids
                )
            else:
                # pod collective: followers must enter the SAME restore +
                # evaluate collectives — broadcast first, evaluate
                # together, then await their acks. A leader-side failure
                # AFTER the broadcast leaves followers inside collectives
                # nothing will complete: the finally still collects what
                # it can (bounded) and the channel poisons the pod on a
                # missing/failed ack.
                eval_channel("start", cfg.job_id, {"chkp_ids": chkp_ids})
                try:
                    metrics = ModelEvaluator(master, mgr).evaluate_checkpoints(
                        chkp_ids, trainer, batch, executor_ids
                    )
                finally:
                    eval_channel("finish", cfg.job_id)
            for cid in chkp_ids:  # consumed: reclaim the disk (the
                # LEADER owns shared-root cleanup; followers never delete)
                mgr.delete(cid)
            return metrics

        return run_eval

    # -- teardown --------------------------------------------------------

    def _cleanup_tables(self) -> None:
        """Release job tables (ref: JobDispatcher drops tables at job end;
        shared/reused tables survive). The master refcounts shared tables:
        every tenant releases its reference and storage is freed only when
        the LAST one does — a creator finishing first must not delete
        buffers under a tenant still training."""
        # Idempotent: the dispatcher calls cleanup() again on exceptions —
        # each handle reference is nulled BEFORE dropping so a second pass
        # (or a drop that raises midway) can never decrement the shared
        # refcount twice and steal another tenant's reference.
        h, self._handle = self._handle, None
        lh, self._local_handle = self._local_handle, None
        if h is not None:
            h.drop()
        if lh is not None:
            lh.drop()

    @property
    def table_handle(self) -> Optional[TableHandle]:
        return self._handle


class PregelJobEntity(JobEntity):
    """Vertex-centric BSP job under the JobServer (ref: the pregel side of
    the app-type switch — pregel/jobserver/PregelJobEntity.java: vertex +
    swapped message tables on the job's executors, PregelMaster run loop).

    Config mapping: ``config.trainer`` names the Computation class;
    ``user.graph_fn``/``user.graph_args`` build the Graph (the analogue of
    the reference's vertex-file bulk load); ``user.max_supersteps`` bounds
    the run. Computation classes that take the graph (PageRank's out-degree
    normalization) receive it as a ``graph=`` kwarg."""

    def __init__(
        self,
        config: JobConfig,
        global_taskunit: Optional[GlobalTaskUnitScheduler] = None,
        local_taskunit: Optional[LocalTaskUnitScheduler] = None,
        metric_sink=None,
        chkp_root: Optional[str] = None,
        metric_manager=None,  # no per-table optimizer loop for graphs
        pod_plan_sink=None,   # accepted for interface parity; graphs have
        pod_eval_channel=None,  # no model table to migrate/evaluate by plan
        pod_unit_scope=None,
        pod_unit_contended=None,  # supersteps have no window to shrink
    ) -> None:
        super().__init__(config, chkp_root)  # no model table: root unused
        self._global_tu = global_taskunit
        self._local_tu = local_taskunit
        # Cross-job pod units (share-all tenancy): the master wraps every
        # superstep dispatch — and setup wraps table creation — in
        # leader-granted units, exactly like dolphin entities.
        self._pod_unit_scope = pod_unit_scope
        self._pregel_master = None
        self._registered = False

    def setup(self, master: ETMaster, executor_ids: List[str]) -> None:
        import contextlib
        import inspect

        from harmony_tpu.parallel.mesh import build_mesh
        from harmony_tpu.pregel.master import PregelMaster

        cfg = self.config
        user = cfg.user
        if "graph_fn" not in user:
            raise ValueError(f"job {cfg.job_id}: user.graph_fn missing")
        graph = resolve_symbol(user["graph_fn"])(**user.get("graph_args", {}))
        comp_cls = resolve_symbol(cfg.trainer)
        app_params = dict(cfg.params.app_params)
        if "graph" in inspect.signature(comp_cls.__init__).parameters:
            app_params["graph"] = graph
        computation = comp_cls(**app_params)
        devices = [master.executor(e).device for e in executor_ids]
        mesh = build_mesh(devices, data=1)
        taskunit = None
        if (self._global_tu is not None and self._local_tu is not None
                and self._pod_unit_scope is None):
            # local TaskUnit admission, like dolphin: dropped under pod
            # units (ordering + fairness come from the arbiter)
            wid = f"{cfg.job_id}/w0"
            self._global_tu.on_job_start(cfg.job_id, [wid])
            self._registered = True
            taskunit = TaskUnitClient(cfg.job_id, wid, self._global_tu, self._local_tu)
        scope = (self._pod_unit_scope() if self._pod_unit_scope is not None
                 else contextlib.nullcontext())
        try:
            with scope:  # table creation + seeds dispatch global programs
                self._pregel_master = PregelMaster(
                    graph,
                    computation,
                    mesh,
                    max_supersteps=int(user.get("max_supersteps", 100)),
                    taskunit=taskunit,
                    job_id=cfg.job_id,
                    dispatch_turn=self._pod_unit_scope,
                )
        except BaseException:
            self._deregister()  # a failed setup must not leave a stale quorum
            raise

    def _deregister(self) -> None:
        if self._registered and self._global_tu is not None:
            self._global_tu.on_executor_done(self.config.job_id,
                                             f"{self.config.job_id}/w0")
            self._global_tu.on_job_finish(self.config.job_id)
            self._registered = False

    def run(self) -> Dict[str, Any]:
        # Deregister in finally: a job that dies mid-superstep must not leave
        # its quorum entry in the global TaskUnit scheduler (stale quorums
        # deadlock other jobs' wait_ready on the long-running server).
        try:
            return self._pregel_master.run()
        finally:
            self._deregister()

    def cleanup(self) -> None:
        if self._pregel_master is not None:
            self._pregel_master.close()


def build_entity(config: JobConfig, **kwargs) -> JobEntity:
    """App-type dispatch (ref: JobEntity.getJobEntity app-type switch)."""
    if config.app_type == "dolphin":
        return DolphinJobEntity(config, **kwargs)
    if config.app_type == "pregel":
        return PregelJobEntity(config, **kwargs)
    raise ValueError(f"unknown app_type {config.app_type!r}")
