"""Pluggable global job scheduling.

Parity with the reference's JobScheduler SPI (jobserver/driver/
JobScheduler.java: onJobArrival / onJobFinish / onResourceChange, pluggable
via the -scheduler flag, bin/start_jobserver.sh:21) and its default
SchedulerImpl, which runs every job immediately on ALL executors —
multi-tenant overlap on the shared pool (SchedulerImpl.java:28-66).

Also ships a FIFO-exclusive policy (jobs get the whole pool one at a time)
as the second built-in, mirroring how the reference's pluggability was
actually used.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from harmony_tpu.config.params import JobConfig

# Callback the server provides: actually launch the job on these executors.
LaunchFn = Callable[[JobConfig, List[str]], None]


class JobScheduler:
    """SPI. Implementations decide when a job runs and on which executors."""

    def bind(self, executor_ids: List[str], launch: LaunchFn) -> None:
        self._executors = list(executor_ids)
        self._launch = launch
        # eager policy-target init: plan_grant (the policy thread) and
        # reacquire (dispatch threads) both touch the map, and the base
        # class is lockless — creating it HERE, before any job exists,
        # removes the lazy-init race that could silently drop a pin
        if getattr(self, "_policy_target_map", None) is None:
            self._policy_target_map: Dict[str, Tuple[List[str], bool]] = {}

    # -- policy-engine SPI (jobserver/policy.py) -------------------------

    def _policy_targets(self) -> Dict[str, Tuple[List[str], bool]]:
        """The ``job_id -> (executors, shared)`` map of policy-planned
        grants, created in :meth:`bind` (lazy fallback for direct-
        constructed test doubles that never bind)."""
        t = getattr(self, "_policy_target_map", None)
        if t is None:
            t = self._policy_target_map = {}
        return t

    def plan_grant(self, job_id: str, executors: Optional[List[str]],
                   shared: bool = False) -> None:
        """Pin the NEXT :meth:`reacquire` grant for ``job_id`` to this
        executor set (the policy engine's actuator: the grant lands when
        the elastic fence ends the running attempt). ``shared=True``
        allows the grant to OVERLAP other tenants' slices (pack/preempt
        — ShareAll-style sharing arbitrated by the TaskUnit fair
        queue). ``executors=None`` clears the pin. One-shot: consumed by
        whichever reacquire runs next for the job."""
        if executors is None:
            self._policy_targets().pop(job_id, None)
        else:
            self._policy_targets()[job_id] = (list(executors), bool(shared))

    def planned_grant(self, job_id: str
                      ) -> Optional[Tuple[List[str], bool]]:
        return self._policy_targets().get(job_id)

    def _policy_async(self) -> Dict[str, bool]:
        """``job_id -> enable`` pins from :meth:`plan_async` (same lazy
        shape as the grant map, for direct-constructed test doubles)."""
        t = getattr(self, "_policy_async_map", None)
        if t is None:
            t = self._policy_async_map = {}
        return t

    def plan_async(self, job_id: str, enabled: bool = True) -> None:
        """Pin bounded-staleness async step mode for ``job_id``'s NEXT
        attempt (the policy engine's `async` actuator — a comm-bound
        tenant's comm phases overlap compute instead of growing it).
        Like :meth:`plan_grant`, the pin lands when the elastic fence
        ends the running attempt; the launcher consumes it via
        :meth:`planned_async` when building the attempt's TrainerParams
        (``async_step`` / ``staleness_bound``). One-shot."""
        self._policy_async()[job_id] = bool(enabled)

    def planned_async(self, job_id: str) -> Optional[bool]:
        """Consume (pop) the async pin for ``job_id``, if any."""
        return self._policy_async().pop(job_id, None)

    def idle_executors(self) -> List[str]:
        """Executors no running job holds — the policy engine's grow
        fodder. Overlap schedulers (share-all) have no idle notion and
        report none."""
        return []

    def idle_units(self) -> List[List[str]]:
        """Idle capacity in GRANT units: the indivisible executor
        groups a policy grow may take (one executor each by default;
        whole host processes on a process-carved pod — the planner must
        never split a process between exclusive tenants)."""
        return [[e] for e in self.idle_executors()]

    def queued_jobs(self) -> List[JobConfig]:
        """Arrivals waiting for capacity (the policy engine's contention
        signal). Non-queueing schedulers report none."""
        return []

    def on_job_arrival(self, config: JobConfig) -> None:
        raise NotImplementedError

    def on_job_finish(self, job_id: str) -> None:
        raise NotImplementedError

    def on_resource_change(self, executor_ids: List[str]) -> None:
        self._executors = list(executor_ids)

    def retire(self, executor_ids: List[str]) -> None:
        """Remove executors from future grants (a pod follower died or
        went silent; its devices cannot serve while it is gone). Running
        grants are untouched — their jobs fail through their own paths.
        No longer permanent: :meth:`restore` reverses it when a silenced
        follower's heartbeats resume or a replacement process JOINs."""
        gone = set(executor_ids)
        self._executors = [e for e in self._executors if e not in gone]

    def restore(self, executor_ids: List[str]) -> None:
        """Re-admit previously retired executors (elastic rehabilitation:
        a confined follower proved itself alive again, or a replacement
        JOINed with the same executor allocation order)."""
        known = set(self._executors)
        self._executors.extend(e for e in executor_ids if e not in known)

    def reacquire(self, job_id: str, preferred: List[str]) -> List[str]:
        """Elastic in-place recovery grant: the SAME submission needs
        executors for its next attempt, preferring the previous grant's
        survivors (minimal data movement). Returns the granted executor
        ids ([] = nothing available; recovery fails over to a plain job
        failure). A policy-planned grant (:meth:`plan_grant`) wins when
        one is pinned — that is how the policy engine's fenced actions
        land. Default (share-all semantics): the surviving preferred
        set, else every live executor."""
        tgt = self._policy_targets().pop(job_id, None)
        if tgt is not None:
            execs = [e for e in tgt[0] if e in self._executors]
            if execs:
                return execs
        alive = [e for e in preferred if e in self._executors]
        return alive or list(self._executors)


class ShareAllScheduler(JobScheduler):
    """Default: every job starts immediately on ALL executors (the
    reference's SchedulerImpl multi-tenant overlap)."""

    def on_job_arrival(self, config: JobConfig) -> None:
        self._launch(config, list(self._executors))

    def on_job_finish(self, job_id: str) -> None:
        pass


class FifoExclusiveScheduler(JobScheduler):
    """One job at a time on the whole pool; arrivals queue."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queue: Deque[JobConfig] = deque()
        self._running: Optional[str] = None

    def on_job_arrival(self, config: JobConfig) -> None:
        with self._lock:
            if self._running is not None:
                self._queue.append(config)
                return
            self._running = config.job_id
        self._launch(config, list(self._executors))

    def on_job_finish(self, job_id: str) -> None:
        nxt = None
        with self._lock:
            if self._running == job_id:
                self._running = None
                if self._queue:
                    nxt = self._queue.popleft()
                    self._running = nxt.job_id
        if nxt is not None:
            self._launch(nxt, list(self._executors))


class CarveScheduler(JobScheduler):
    """Mesh carving: every job gets a DISJOINT slice of the executor pool
    (the BASELINE north-star sharing mode — jobs share the pod by slicing
    the mesh, not by overlapping on every chip like ShareAll). Fair share
    at arrival = pool // (running jobs + 1), floored at ``min_slice``;
    arrivals that cannot get ``min_slice`` free executors queue FIFO, and
    a finishing job returns its slice (launching queued jobs first)."""

    def __init__(self, min_slice: int = 1, max_share: Optional[int] = None) -> None:
        """``max_share`` caps any one job's slice — without it the FIRST
        arrival's fair share is the whole idle pool and later jobs queue
        behind it; set e.g. pool//2 to leave room for concurrent tenants."""
        if min_slice < 1:
            raise ValueError("min_slice must be >= 1")
        if max_share is not None and max_share < min_slice:
            raise ValueError("max_share must be >= min_slice")
        self.min_slice = min_slice
        self.max_share = max_share
        self._lock = threading.Lock()
        self._free: List[str] = []
        self._slices: Dict[str, List[str]] = {}
        self._queue: Deque[JobConfig] = deque()

    def bind(self, executor_ids: List[str], launch: LaunchFn) -> None:
        super().bind(executor_ids, launch)
        self._free = list(executor_ids)

    def retire(self, executor_ids: List[str]) -> None:
        """Dead executors must leave the FREE pool too (under the lock,
        against concurrent slice grants), or the next _take_slice hands
        them to a job that can only fail pod admission."""
        gone = set(executor_ids)
        with self._lock:
            super().retire(executor_ids)
            self._free = [e for e in self._free if e not in gone]

    def restore(self, executor_ids: List[str]) -> None:
        """Rehabilitated executors rejoin the free pool (and may unblock
        queued arrivals) unless some job's live slice already claims
        them."""
        with self._lock:
            super().restore(executor_ids)
            sliced = {e for sl in self._slices.values() for e in sl}
            self._free.extend(
                e for e in executor_ids
                if e not in sliced and e not in self._free
            )
            launches = self._drain_queue_locked()
        for cfg, sl in launches:
            self._launch(cfg, sl)

    def _claim_target_locked(self, job_id: str,
                             tgt: "Tuple[List[str], bool]") -> List[str]:
        """Under the lock: land a policy-planned grant. Exclusive
        targets take only still-free executors (a concurrent arrival
        may have claimed some since the plan); shared targets overlap
        live slices by design (pack/preempt). [] = plan no longer
        satisfiable — the caller falls back to the normal grant."""
        execs, shared = tgt
        known = set(self._executors)
        execs = [e for e in execs if e in known]
        if not shared:
            free = set(self._free)
            execs = [e for e in execs if e in free]
        if not execs:
            return []
        taken = set(execs)
        self._free = [e for e in self._free if e not in taken]
        self._slices[job_id] = execs
        return execs

    def idle_executors(self) -> List[str]:
        with self._lock:
            return list(self._free)

    def queued_jobs(self) -> List[JobConfig]:
        with self._lock:
            return list(self._queue)

    def reacquire(self, job_id: str, preferred: List[str]) -> List[str]:
        """In-place recovery grant: a policy-planned target wins when
        still satisfiable; else take the still-free survivors of the
        previous grant; if none survive, carve a fresh slice. The grant
        registers under ``job_id`` so the attempt's on_job_finish returns
        it like any slice (each attempt pairs one reacquire with one
        finish)."""
        with self._lock:
            tgt = self._policy_targets().pop(job_id, None)
            if tgt is not None:
                take = self._claim_target_locked(job_id, tgt)
                if take:
                    return take
            free = set(self._free)
            take = [e for e in preferred if e in free]
            if not take:
                take = self._take_slice() or []
            else:
                taken = set(take)
                self._free = [e for e in self._free if e not in taken]
            if take:
                self._slices[job_id] = take
        return take

    def _take_slice(self) -> Optional[List[str]]:
        """Under the lock: carve the next job's slice or None to queue."""
        share = max(
            self.min_slice, len(self._executors) // (len(self._slices) + 1)
        )
        if self.max_share is not None:
            share = min(share, self.max_share)
        if len(self._free) < self.min_slice:
            return None
        take = self._free[: min(share, len(self._free))]
        del self._free[: len(take)]
        return take

    def on_job_arrival(self, config: JobConfig) -> None:
        with self._lock:
            sl = self._take_slice()
            if sl is None:
                self._queue.append(config)
                return
            self._slices[config.job_id] = sl
        self._launch(config, sl)

    def on_job_finish(self, job_id: str) -> None:
        launches = []
        with self._lock:
            known = set(self._executors)
            mine = self._slices.pop(job_id, [])
            # only still-provisioned executors return to the pool (some
            # may have departed via on_resource_change while the job
            # ran), and never ones another live slice still holds — a
            # shared (packed) grant overlaps slices, so the LAST tenant
            # off an executor frees it
            held = {e for sl in self._slices.values() for e in sl}
            self._free.extend(
                e for e in mine
                if e in known and e not in held and e not in self._free
            )
            launches = self._drain_queue_locked()
        for cfg, sl in launches:
            self._launch(cfg, sl)

    def _drain_queue_locked(self):
        """Under the lock: carve slices for queued jobs while any fit;
        returns the (config, slice) launches to fire outside the lock."""
        launches = []
        while self._queue:
            sl = self._take_slice()
            if sl is None:
                break
            cfg = self._queue.popleft()
            self._slices[cfg.job_id] = sl
            launches.append((cfg, sl))
        return launches

    def on_resource_change(self, executor_ids: List[str]) -> None:
        """Reconcile the free pool with the new executor set: departed
        executors leave _free immediately (running jobs keep their slices
        until they finish — a live re-carve is plan-engine territory), and
        arrivals join _free, possibly unblocking the queue."""
        launches = []
        with self._lock:
            super().on_resource_change(executor_ids)
            known = set(executor_ids)
            sliced = {e for sl in self._slices.values() for e in sl}
            self._free = [e for e in self._free if e in known]
            self._free.extend(
                e for e in executor_ids
                if e not in sliced and e not in self._free
            )
            launches = self._drain_queue_locked()
        for cfg, sl in launches:
            self._launch(cfg, sl)

    def slice_of(self, job_id: str) -> List[str]:
        with self._lock:
            return list(self._slices.get(job_id, []))


class ProcessCarveScheduler(CarveScheduler):
    """Mesh carving in whole-HOST-PROCESS units, for multi-host pods.

    On a pod, two concurrent jobs are hazard-free only when their XLA
    programs never share a process: disjoint process sets cannot form a
    cross-process enqueue-order cycle (see jobserver/pod.py's admission
    rule). This scheduler guarantees that shape by construction — every
    slice is a set of COMPLETE processes, so the PodJobServer dispatches
    all carved jobs concurrently. Fair share at arrival = total processes
    // (running jobs + 1), floored at ``min_procs``.

    The executor->process map is injected by the server after allocation
    (``set_process_map``); until then the scheduler treats the pool as one
    process (degenerating to FIFO-exclusive, which is safe)."""

    def __init__(self, min_procs: int = 1, max_procs: Optional[int] = None) -> None:
        super().__init__(min_slice=1, max_share=None)
        if min_procs < 1:
            raise ValueError("min_procs must be >= 1")
        if max_procs is not None and max_procs < min_procs:
            raise ValueError("max_procs must be >= min_procs")
        self.min_procs = min_procs
        self.max_procs = max_procs
        self._proc_of: Dict[str, int] = {}

    def set_process_map(self, proc_of: Dict[str, int]) -> None:
        """executor id -> process index (from Executor.device.process_index)."""
        with self._lock:
            self._proc_of = dict(proc_of)

    def reacquire(self, job_id: str, preferred: List[str]) -> List[str]:
        """Whole-process recovery grant: survivors are kept only as
        COMPLETE free processes (a partial process in a recovery grant
        would break the disjoint-process concurrency guarantee every
        carved tenant relies on); otherwise a fresh whole-process slice
        is carved. A policy-planned grant wins when satisfiable — the
        planner composes pod targets from :meth:`idle_units` (whole
        processes), and :meth:`_claim_target_locked` re-validates the
        shape as the backstop."""
        with self._lock:
            tgt = self._policy_targets().pop(job_id, None)
            if tgt is not None:
                take = self._claim_target_locked(job_id, tgt)
                if take:
                    return take
            free = set(self._free)
            wanted = set(preferred)
            members: Dict[int, List[str]] = {}
            for e in self._executors:
                members.setdefault(self._proc_of.get(e, 0), []).append(e)
            take = [
                e for p, mem in sorted(members.items())
                # the WHOLE process must be both preferred and free — a
                # half-claimed process is exactly the shape the carve
                # exists to forbid
                if mem and wanted >= set(mem) and free >= set(mem)
                for e in mem
            ]
            if not take:
                take = self._take_slice() or []
            else:
                taken = set(take)
                self._free = [e for e in self._free if e not in taken]
            if take:
                self._slices[job_id] = take
        return take

    def _claim_target_locked(self, job_id: str,
                             tgt: "Tuple[List[str], bool]") -> List[str]:
        """Whole-process backstop for policy grants: an EXCLUSIVE
        target that splits any process is rejected outright (the
        normal reacquire path then grants) — a half-claimed process is
        exactly the shape this scheduler exists to forbid. Shared
        (pack/preempt) targets overlap by design and pass through."""
        execs, shared = tgt
        if not shared:
            members: Dict[int, List[str]] = {}
            for e in self._executors:
                members.setdefault(self._proc_of.get(e, 0), []).append(e)
            want = set(execs) & set(self._executors)
            for p, mem in members.items():
                if want & set(mem) and not want >= set(mem):
                    return []
        return super()._claim_target_locked(job_id, tgt)

    def idle_units(self) -> List[List[str]]:
        """Idle capacity in whole-process units — the only grant shape
        a policy grow may take here."""
        with self._lock:
            members: Dict[int, List[str]] = {}
            for e in self._executors:
                members.setdefault(self._proc_of.get(e, 0), []).append(e)
            free = set(self._free)
            return [list(mem) for _p, mem in sorted(members.items())
                    if mem and free >= set(mem)]

    def _take_slice(self) -> Optional[List[str]]:
        """Under the lock: carve whole free processes or None to queue."""
        proc_members: Dict[int, List[str]] = {}
        for e in self._executors:
            proc_members.setdefault(self._proc_of.get(e, 0), []).append(e)
        free = set(self._free)
        free_procs = sorted(
            p for p, members in proc_members.items()
            if all(e in free for e in members)
        )
        share = max(
            self.min_procs, len(proc_members) // (len(self._slices) + 1)
        )
        if self.max_procs is not None:
            share = min(share, self.max_procs)
        if len(free_procs) < self.min_procs:
            return None
        take_procs = free_procs[: min(share, len(free_procs))]
        take = [e for p in take_procs for e in proc_members[p]]
        self._free = [e for e in self._free if e not in set(take)]
        return take


_SCHEDULERS: Dict[str, type] = {
    "share_all": ShareAllScheduler,
    "fifo": FifoExclusiveScheduler,
    "carve": CarveScheduler,
    "pod_carve": ProcessCarveScheduler,
}


def make_scheduler(name: str) -> JobScheduler:
    """Scheduler-by-name (the -scheduler flag analogue)."""
    try:
        return _SCHEDULERS[name]()
    except KeyError:
        raise KeyError(f"unknown scheduler {name!r}; have {sorted(_SCHEDULERS)}") from None
