"""Pluggable global job scheduling.

Parity with the reference's JobScheduler SPI (jobserver/driver/
JobScheduler.java: onJobArrival / onJobFinish / onResourceChange, pluggable
via the -scheduler flag, bin/start_jobserver.sh:21) and its default
SchedulerImpl, which runs every job immediately on ALL executors —
multi-tenant overlap on the shared pool (SchedulerImpl.java:28-66).

Also ships a FIFO-exclusive policy (jobs get the whole pool one at a time)
as the second built-in, mirroring how the reference's pluggability was
actually used.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from harmony_tpu.config.params import JobConfig

# Callback the server provides: actually launch the job on these executors.
LaunchFn = Callable[[JobConfig, List[str]], None]


class JobScheduler:
    """SPI. Implementations decide when a job runs and on which executors."""

    def bind(self, executor_ids: List[str], launch: LaunchFn) -> None:
        self._executors = list(executor_ids)
        self._launch = launch

    def on_job_arrival(self, config: JobConfig) -> None:
        raise NotImplementedError

    def on_job_finish(self, job_id: str) -> None:
        raise NotImplementedError

    def on_resource_change(self, executor_ids: List[str]) -> None:
        self._executors = list(executor_ids)


class ShareAllScheduler(JobScheduler):
    """Default: every job starts immediately on ALL executors (the
    reference's SchedulerImpl multi-tenant overlap)."""

    def on_job_arrival(self, config: JobConfig) -> None:
        self._launch(config, list(self._executors))

    def on_job_finish(self, job_id: str) -> None:
        pass


class FifoExclusiveScheduler(JobScheduler):
    """One job at a time on the whole pool; arrivals queue."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queue: Deque[JobConfig] = deque()
        self._running: Optional[str] = None

    def on_job_arrival(self, config: JobConfig) -> None:
        with self._lock:
            if self._running is not None:
                self._queue.append(config)
                return
            self._running = config.job_id
        self._launch(config, list(self._executors))

    def on_job_finish(self, job_id: str) -> None:
        nxt = None
        with self._lock:
            if self._running == job_id:
                self._running = None
                if self._queue:
                    nxt = self._queue.popleft()
                    self._running = nxt.job_id
        if nxt is not None:
            self._launch(nxt, list(self._executors))


_SCHEDULERS: Dict[str, type] = {
    "share_all": ShareAllScheduler,
    "fifo": FifoExclusiveScheduler,
}


def make_scheduler(name: str) -> JobScheduler:
    """Scheduler-by-name (the -scheduler flag analogue)."""
    try:
        return _SCHEDULERS[name]()
    except KeyError:
        raise KeyError(f"unknown scheduler {name!r}; have {sorted(_SCHEDULERS)}") from None
