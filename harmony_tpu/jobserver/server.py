"""JobServer — the long-running multi-tenant master.

Parity with the reference's jobserver (SURVEY.md §2.5):

  * lifecycle state machine NOT_INIT -> INIT -> CLOSED
    (ref: JobServerDriver.java:56-305),
  * ResourcePool: acquire N homogeneous executors from the ETMaster once at
    startup; all jobs share them (ref: ResourcePool.java:39-106),
  * submit handling: deserialize the job config, build the JobEntity, hand
    to the pluggable JobScheduler (ref: submit handling
    JobServerDriver.java:239-257),
  * JobDispatcher: per job — setup tables -> register -> TaskUnit
    on_job_start -> run -> drop tables -> deregister -> scheduler
    on_job_finish (ref: JobDispatcher.java:55-87),
  * graceful shutdown waits for running jobs (ref: shutdown 178-214),
  * a TCP command endpoint on localhost accepting SUBMIT/SHUTDOWN
    (ref: CommandSender/Listener socket protocol, client/CommandSender.java:
    49-80) — see client.py for the wire format.
"""
from __future__ import annotations

import json
import queue as _queue
import socket
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional

from harmony_tpu.config.base import ConfigBase
from harmony_tpu.config.params import JobConfig
from harmony_tpu.jobserver.entity import JobEntity, build_entity
from harmony_tpu.jobserver.joblog import job_logger, server_log
from harmony_tpu.jobserver.overload import OverloadMonitor
from harmony_tpu.jobserver.scheduler import JobScheduler, ShareAllScheduler, make_scheduler
from harmony_tpu.metrics.doctor import Doctor, set_doctor
from harmony_tpu.metrics.history import HistoryScraper, HistoryStore, extra_targets
from harmony_tpu.metrics.manager import MetricManager
from harmony_tpu.parallel.mesh import DevicePool
from harmony_tpu.runtime.master import ETMaster
from harmony_tpu.runtime.taskunit import GlobalTaskUnitScheduler, LocalTaskUnitScheduler
from harmony_tpu.tracing.span import (
    SpanContext,
    current_span,
    get_tracing,
    trace_span,
    wire_context,
)
from harmony_tpu.utils.statemachine import StateMachine


class NotLeader(RuntimeError):
    """Raised by submit() when the durable submission record was refused
    because this leader's lease lapsed mid-command (deposed between the
    TCP gate check and the append). The command plane converts it into
    the NOT_LEADER reply so the client retries on the successor — an
    acknowledged submission is ALWAYS in the replicated log."""


class JobResult:
    def __init__(self) -> None:
        self.future: "Future[Dict[str, Any]]" = Future()


def _json_sanitize(obj: Any) -> Any:
    """Best-effort JSON projection of a job result for the wire: plain
    scalars/containers pass through, numpy scalars coerce, anything else
    (device arrays, closures) becomes its repr — the WAIT/chief-report
    paths must never fail on an exotic result value."""
    if isinstance(obj, dict):
        return {str(k): _json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_sanitize(v) for v in obj]
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    try:
        import numpy as np

        if isinstance(obj, np.generic):
            return obj.item()
    except Exception:
        pass
    return repr(obj)


class JobServer:
    def __init__(
        self,
        num_executors: int,
        scheduler: Optional[JobScheduler | str] = None,
        device_pool: Optional[DevicePool] = None,
        cpu_slots: int = 1,
        net_slots: int = 2,
        chkp_root: Optional[str] = None,
        dashboard_url: Optional[str] = None,
    ) -> None:
        if isinstance(scheduler, str):
            scheduler = make_scheduler(scheduler)  # the -scheduler flag analogue
        self._state = StateMachine(
            states=["NOT_INIT", "INIT", "CLOSING", "CLOSED"],
            transitions=[
                ("NOT_INIT", "INIT"),
                ("INIT", "CLOSING"),
                ("CLOSING", "CLOSED"),
            ],
            initial="NOT_INIT",
        )
        self.master = ETMaster(device_pool)
        self.metrics = MetricManager()
        self.metrics.start_collection()
        # Live metrics to a dashboard (ref: DolphinDriver POSTing to the
        # Flask dashboard via DashboardConnector.java:30-100): every job
        # metric tees to the async connector, which drops rather than
        # blocks when the dashboard is slow or down.
        self._dashboard = None
        self._span_receiver = None
        if dashboard_url:
            from harmony_tpu.dashboard.connector import (
                DashboardConnector,
                DashboardSpanReceiver,
            )

            self._dashboard = DashboardConnector(dashboard_url)
            # finished spans tee to the dashboard's span store (async,
            # drop-don't-block like every other dashboard post) so its
            # per-job trace/timeline view renders real control-plane
            # traces, not only metric rows
            self._span_receiver = get_tracing().add_receiver(
                DashboardSpanReceiver(self._dashboard)
            )
        # the crash-correlated flight recorder starts capturing spans the
        # moment a server exists in this process (tracing/flight.py)
        from harmony_tpu.tracing import flight as _flight

        _flight.get_recorder()
        # per-process Prometheus endpoint (HARMONY_METRICS_PORT; None
        # when the knob is unset — tests and one-shots pay nothing)
        from harmony_tpu.metrics.exporter import exporter_from_env

        self.metrics_exporter = exporter_from_env()
        self.global_taskunit = GlobalTaskUnitScheduler()
        self.local_taskunit = LocalTaskUnitScheduler(cpu_slots, net_slots)
        self._scheduler = scheduler or ShareAllScheduler()
        self._num_executors = num_executors
        self._chkp_root = chkp_root
        self._jobs: Dict[str, JobResult] = {}
        self._entities: Dict[str, JobEntity] = {}
        # Deferred model evaluations, run during graceful shutdown (ref:
        # JobServerDriver.java:178-214). job_id -> closure(master).
        self._deferred_evals: Dict[str, Any] = {}
        self.eval_results: Dict[str, Any] = {}
        self._dispatch_threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._tcp_thread: Optional[threading.Thread] = None
        self._tcp_sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        # Bounded command plane (jobserver/overload.py): a fixed worker
        # pool drains a bounded accept queue; the monitor watches queue
        # lag + telemetry-cycle overrun and steps the degradation
        # ladder. Built unconditionally — admission questions are asked
        # even when serve_tcp never runs (direct submit() callers).
        self.overload = OverloadMonitor()
        self._cmd_queue: Optional["_queue.Queue"] = None
        self._cmd_workers: List[threading.Thread] = []
        self._cmd_queue_cap = 0
        # Embedded input-data service (harmony_tpu/inputsvc): started on
        # demand when the first opted-in job arrives — scheduled and
        # owned by the jobserver like any other tenant resource, scaled
        # by the ledger-fed autoscaler, surfaced via STATUS.
        self.input_service = None
        self._input_autoscaler = None
        # Embedded serving plane (harmony_tpu/serving): started on
        # demand by the first SERVING command — request-scale reads of
        # live training state, micro-batched onto the sparse gather and
        # admission-controlled by the same overload ladder as commands.
        self.serving = None
        # Telemetry history + root-cause doctor (metrics/history.py +
        # metrics/doctor.py): a jobserver-side scraper polls every known
        # process's /metrics (the leader's own registry in-process, pod
        # followers via their heartbeat-advertised exporter ports) and
        # the tenant ledger into a bounded time-series store; the doctor
        # evaluates its rule catalog after every poll. Diagnoses land as
        # kind="diagnosis" joblog events, ride STATUS, and tee to the
        # dashboard when one is configured.
        self.history = HistoryStore()
        self.doctor = Doctor(
            self.history,
            stragglers_fn=self.metrics.straggler_report,
            sinks=(self._post_diagnosis,),
        )
        set_doctor(self.doctor)
        self._history_scraper = HistoryScraper(
            self.history,
            targets_fn=self._scrape_targets,
            ledger_fn=self.metrics.tenant_ledger,
            on_cycle=self._on_scrape_cycle,
        )
        # Device policy engine (jobserver/policy.py): each window it
        # reads the ledger + diagnoses + critpath verdicts and replans
        # placement through the elastic fences — grow under-SLO tenants
        # onto idle executors, shrink/pack/preempt low-priority tenants
        # under contention. HARMONY_POLICY selects off/advise/act; the
        # plain server has no elastic actuator, so it advises; the pod
        # server overrides the tenants/fence hooks with real ones.
        from harmony_tpu.jobserver.policy import PolicyEngine

        self.policy = PolicyEngine(
            scheduler=self._scheduler,
            ledger_fn=self.metrics.tenant_ledger,
            tenants_fn=self._policy_tenants,
            fence_fn=self._policy_fence,
            diagnoses_fn=self.doctor.recent,
            leader_ok_fn=self._ha_leader_ok,
            sinks=(self._post_policy,),
        )
        # Incident correlation (metrics/incidents.py): folds the joblog
        # stream + flight-ring fault evidence into open→mitigating→
        # resolved incidents with causal chains and MTTD/MTTR. Runs on
        # the same scrape cycle as the doctor/policy; incidents persist
        # as kind="incident" joblog events so the HA tee makes them
        # survive a leader takeover (ha.py adopts the replayed set).
        from harmony_tpu.metrics.incidents import IncidentEngine, \
            set_incidents

        self.incidents = IncidentEngine(sinks=(self._post_incident,))
        set_incidents(self.incidents)
        # Control-plane HA (jobserver/ha.py): wired by enable_ha when
        # this server is one replica of an HA control plane. leader_epoch
        # stamps every durable log entry and pod RUN_JOB/PLAN message so
        # a deposed leader's late writes are fenced everywhere.
        self.ha_log = None
        self.ha_lease = None
        self.ha_replicator = None
        self.ha_replica_id: Optional[str] = None
        self.leader_epoch = 0
        self._ha_sink = None

    # -- control-plane HA ------------------------------------------------

    def enable_ha(self, log, lease=None, replicator=None,
                  replica_id: Optional[str] = None) -> None:
        """Wire the durable replicated job log (+ lease + replicator)
        into this server: every structured joblog event tees into the
        log, submissions/completions get first-class durable entries,
        and the leader epoch fences RUN_JOB/PLAN broadcasts. Call
        BEFORE start(); jobserver/ha.py's takeover does."""
        from harmony_tpu.jobserver import joblog

        def sink(job_id: str, ev: Dict[str, Any]) -> None:
            self._ha_append(ev.get("kind", "event"), job_id=job_id,
                            **{k: v for k, v in ev.items()
                               if k not in ("kind", "ts")})

        with self._lock:
            self.ha_log = log
            self.ha_lease = lease
            self.ha_replicator = replicator
            self.ha_replica_id = replica_id
            self.leader_epoch = (lease.epoch if lease is not None
                                 else log.fence_epoch)
            self._ha_sink = sink
        log.set_epoch(self.leader_epoch)
        joblog.add_sink(sink)
        if replicator is not None:
            replicator.start()

    def _ha_leader_ok(self) -> bool:
        """False once a held lease has lapsed — the deposed state in
        which every mutating command answers NOT_LEADER and durable
        appends are refused (split-brain fencing, local half)."""
        return self.ha_lease is None or self.ha_lease.is_valid()

    def _not_leader_reply(self) -> Dict[str, Any]:
        """The structured NOT_LEADER redirect, with the current lease
        holder's advertised address when the lease store knows one."""
        hint = None
        if self.ha_lease is not None:
            import os as _os

            from harmony_tpu.jobserver.lease import leader_hint

            hint = leader_hint(
                _os.path.dirname(self.ha_lease.path),
                own_holder_id=self.ha_lease.holder_id)
        return {"ok": False, "not_leader": True,
                "error": "NOT_LEADER: this replica's lease "
                         "lapsed (deposed)",
                "leader": hint}

    #: entry-envelope keys DurableJobLog.append owns; event fields that
    #: collide (elastic fences carry their own ``epoch``, diagnoses a
    #: ``job``) are namespaced ``ev_*`` so the tee can never clash with
    #: the envelope — or silently corrupt seq/epoch fencing
    _HA_RESERVED = ("seq", "epoch", "ts", "kind", "job")

    def _ha_append(self, kind: str, job_id: Optional[str] = None,
                   **fields: Any) -> bool:
        """Guarded durable append: never fails the serving path, drops
        (loudly) once this leader is deposed. Returns False when the
        entry did NOT land durably — the deposed drop, or an append
        error (ENOSPC/EIO on the log disk). A caller whose ack DEPENDS
        on the entry (submit()'s submission record) must refuse on
        False; the telemetry tees ignore it (best-effort as before).
        The chaos sweep's halog-ENOSPC schedule caught the old
        swallow-and-ack shape handing out acks no successor could ever
        replay."""
        if self.ha_log is None:
            return True
        if not self._ha_leader_ok():
            server_log.warning(
                "halog append %r dropped: this leader's lease lapsed "
                "(deposed)", kind)
            return False
        try:
            fields = {(f"ev_{k}" if k in self._HA_RESERVED else k): v
                      for k, v in fields.items()}
            self.ha_log.append(kind, job_id=job_id,
                               epoch=self.leader_epoch, **fields)
        except Exception as e:  # noqa: BLE001 - durability is surfaced,
            server_log.error("halog append %r failed: %s: %s",
                             kind, type(e).__name__, e)
            return False
        return True

    def _ha_record_done(self, job_id: str, fut: "Future") -> None:
        exc = fut.exception()
        if exc is None:
            self._ha_append("job_done", job_id=job_id, ok=True)
        else:
            self._ha_append(
                "job_done", job_id=job_id, ok=False,
                error=f"{type(exc).__name__}: {exc}"[:300])

    def _ha_status(self) -> Dict[str, Any]:
        from harmony_tpu.jobserver import joblog

        if self.ha_log is None:
            return {"enabled": False}
        takeovers = [ev for ev in joblog.job_events("__ha__", limit=8)
                     if ev.get("kind") == "leader_takeover"]
        return {
            "enabled": True,
            "role": ("leader" if self._ha_leader_ok() else "deposed"),
            "replica": self.ha_replica_id,
            "leader_epoch": self.leader_epoch,
            "lease": (self.ha_lease.stats()
                      if self.ha_lease is not None else None),
            "log": self.ha_log.stats(),
            "replication": (self.ha_replicator.stats()
                            if self.ha_replicator is not None else None),
            "takeovers": takeovers,
        }

    def _on_metric(self, record) -> None:
        """Every job metric lands in the manager AND (when configured)
        tees to the dashboard connector — the manager is authoritative
        (optimizer/queries); the dashboard is best-effort observability."""
        self.metrics.on_metric(record)
        if self._dashboard is not None:
            self._dashboard.metric_sink(record)
            self._maybe_post_tenants(record)

    #: minimum seconds between tenant-ledger posts to the dashboard —
    #: epoch reports can land at hundreds/sec across tenants, and the
    #: ledger snapshot is a (cheap but nonzero) whole-store walk
    _TENANT_POST_PERIOD = 2.0
    _last_tenant_post = 0.0

    def _maybe_post_tenants(self, record) -> None:
        """Rate-limited tee of the per-tenant cost vectors to the
        dashboard (kind="tenant", one row per job): epoch boundaries are
        the natural cadence — that is when the ledger's numbers move."""
        import time as _time

        from harmony_tpu.metrics.collector import EpochMetrics

        if not isinstance(record, EpochMetrics):
            return
        now = _time.monotonic()
        # under overload the dashboard tee rate-limits HARDER (the
        # ladder's cheapest fidelity shed — it was best-effort anyway)
        period = self._TENANT_POST_PERIOD * self.overload.dashboard_factor()
        if now - self._last_tenant_post < period:
            if now - self._last_tenant_post >= self._TENANT_POST_PERIOD:
                self.overload.count_shed("dashboard_skip")
            return
        self._last_tenant_post = now
        try:
            for jid, row in self.metrics.tenant_ledger().items():
                self._dashboard.post(jid, "tenant", row)
        except Exception:
            pass  # dashboard posts are best-effort by contract

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Acquire the executor pool; become ready for submissions."""
        executors = self.master.add_executors(self._num_executors)
        # execution metering is a blocking-backend concept (see
        # GlobalTaskUnitScheduler.meter_execution)
        self.global_taskunit.meter_execution = all(
            e.device.platform == "cpu" for e in executors
        )
        self._scheduler.bind([e.id for e in executors], self._launch)
        self._history_scraper.start()
        self._state.transition("INIT")
        server_log.info("jobserver up: %d executors, scheduler=%s",
                        len(executors), type(self._scheduler).__name__)

    def shutdown(self, timeout: Optional[float] = 300.0) -> None:
        """Graceful: stop accepting, drain running jobs, close (ref:
        shutdown waits for jobs then runs deferred work,
        JobServerDriver.java:178-214).

        The accept-gate flips FIRST (INIT -> CLOSING, under the registry
        lock so no mid-submit job can slip past it) — then the drain loop
        re-snapshots until no job is left. ``timeout`` bounds the WHOLE
        drain: a wedged job cannot hold shutdown hostage; the server closes
        and the stragglers stay visible through their futures."""
        with self._lock:
            initiated = self._state.compare_and_transition("INIT", "CLOSING")
        if initiated:
            server_log.info("shutdown initiated; draining %d running job(s)",
                            len(self.running_jobs()))
        if not initiated:
            self._state.wait_for("CLOSED", timeout=timeout)
            return
        self._stop_tcp()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                pending = [r for r in self._jobs.values() if not r.future.done()]
            if not pending:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break  # timed out: close anyway, leave stragglers observable
            try:
                pending[0].future.result(timeout=remaining)
            except Exception:
                pass  # failures/timeouts are visible via the futures
        # Join the dispatch threads themselves (not just their futures): a
        # thread still unwinding its finally-block at interpreter exit gets
        # killed mid-C++-teardown and aborts the process. Joins share the
        # same deadline (+ a small grace period when already past it).
        with self._lock:
            threads = list(self._dispatch_threads)
        grace = time.monotonic() + 5.0
        drained = True
        for t in threads:
            limit = grace if deadline is None else max(deadline, grace)
            t.join(timeout=max(0.0, limit - time.monotonic()))
            if t.is_alive():
                drained = False  # straggler still owns its executors
        self._run_deferred_evals(timeout, drained)
        try:
            self._on_closing(timeout)
        finally:
            if self._span_receiver is not None:
                get_tracing().remove_receiver(self._span_receiver)
                self._span_receiver = None
            if self._dashboard is not None:
                self._dashboard.close()  # flush the async queue, then stop
            self._history_scraper.stop()
            from harmony_tpu.metrics.doctor import peek_doctor

            if peek_doctor() is self.doctor:
                set_doctor(None)
            from harmony_tpu.metrics.incidents import peek_incidents, \
                set_incidents as _set_incidents

            if peek_incidents() is self.incidents:
                _set_incidents(None)
            if self.metrics_exporter is not None:
                self.metrics_exporter.stop()
                self.metrics_exporter = None
            self._stop_input_service()
            self._stop_serving()
            self._stop_ha()
            self._state.transition("CLOSED")

    def _stop_ha(self) -> None:
        """HA teardown on graceful shutdown: unhook the joblog tee,
        stop the replication stream, release the lease (so a standby
        takes over immediately instead of waiting out the window), and
        close the log."""
        from harmony_tpu.jobserver import joblog

        with self._lock:
            sink, self._ha_sink = self._ha_sink, None
            replicator, self.ha_replicator = self.ha_replicator, None
            lease, self.ha_lease = self.ha_lease, None
            log, self.ha_log = self.ha_log, None
        if sink is not None:
            joblog.remove_sink(sink)
        if replicator is not None:
            replicator.stop()
        if lease is not None:
            lease.release()
        if log is not None:
            log.close()

    def _on_closing(self, timeout: Optional[float]) -> None:
        """Subclass hook running after the drain + deferred evals but
        BEFORE the CLOSED transition (pod teardown must finish while
        observers still see CLOSING — anything keyed on CLOSED, like the
        worker process exit, may run the instant the state flips)."""

    def _run_deferred_evals(self, timeout: Optional[float], drained: bool) -> None:
        """The deferred-work stage of graceful shutdown (ref:
        JobServerDriver.java:178-214: after the job drain, run the model
        evaluations the Dolphin masters deferred). Failures are recorded per
        job, never raised — shutdown must complete. The stage gets its own
        ``timeout`` budget (shutdown is thus bounded by ~2x timeout): each
        eval runs on a daemon thread and a slow one is abandoned with a
        recorded error, so user eval code cannot hold shutdown hostage.
        If the job drain itself timed out, evals are SKIPPED — stragglers
        still occupy the executors the eval would restore tables onto."""
        with self._lock:
            evals = dict(self._deferred_evals)
            self._deferred_evals.clear()
        if not evals:
            return
        stage_deadline = None if timeout is None else time.monotonic() + timeout
        abandoned = False
        for job_id, fn in evals.items():
            if not drained:
                self.eval_results[job_id] = {
                    "error": "skipped: job drain timed out"
                }
                continue
            if abandoned:
                # an abandoned (timed-out) eval thread may still be
                # running; evals can be multi-process COLLECTIVES, and a
                # second one interleaving with it enqueues programs in
                # orders the followers (strictly sequential) cannot match
                # — skip the rest instead of deadlocking the pod
                self.eval_results[job_id] = {
                    "error": "skipped: a previous eval timed out and may "
                             "still be running"
                }
                continue
            box: Dict[str, Any] = {}

            def call(fn=fn, box=box) -> None:
                try:
                    box["result"] = fn(self.master)
                except Exception as e:  # noqa: BLE001 - recorded below
                    box["error"] = f"{type(e).__name__}: {e}"

            t = threading.Thread(
                target=call, daemon=True, name=f"deferred-eval-{job_id}"
            )
            t.start()
            remaining = (
                None if stage_deadline is None
                else max(0.0, stage_deadline - time.monotonic())
            )
            t.join(timeout=remaining)
            if t.is_alive():
                self.eval_results[job_id] = {"error": "timed out"}
                abandoned = True  # its thread may still be mid-collective
            elif "error" in box:
                self.eval_results[job_id] = {"error": box["error"]}
            else:
                self.eval_results[job_id] = box["result"]

    @property
    def state(self) -> str:
        return self._state.state

    # -- submission ------------------------------------------------------

    def submit(self, config: JobConfig) -> "Future[Dict[str, Any]]":
        """SUBMIT: schedule a job; returns a future for its result.

        Trace threading: the submitter's span context rides inside the
        config (``user["_trace"]`` — already set by the TCP ingest when
        the CLI sent one, else captured from the ambient span here), so
        the dispatch thread, the pod legs and the workers all re-parent
        onto ONE submission trace across threads and processes."""
        if "_trace" not in config.user:
            wire = wire_context()
            if wire is not None:
                config.user["_trace"] = wire
        from harmony_tpu import inputsvc

        if inputsvc.enabled_for(config.params):
            # before scheduling: the workers resolve the endpoint at
            # dispatch time, so the service must exist by then
            self._ensure_input_service()
        with self._lock:
            # State checked under the registry lock: shutdown's INIT->CLOSING
            # flip holds the same lock, so a submit can't interleave between
            # the check and registration and launch after the drain.
            if not self._state.is_state("INIT"):
                raise RuntimeError(f"server not accepting jobs (state={self.state})")
            existing = self._jobs.get(config.job_id)
            if existing is not None and not existing.future.done():
                raise ValueError(f"duplicate job id {config.job_id} (still running)")
            if len(self._jobs) > 1024:  # bound registry growth on long-lived servers
                for jid in [j for j, r in self._jobs.items() if r.future.done()]:
                    del self._jobs[jid]
            jr = JobResult()
            self._jobs[config.job_id] = jr
        job_logger(config.job_id).info(
            "submitted (app_type=%s, workers=%d)",
            config.app_type, config.num_workers,
        )
        if self.ha_log is not None:
            # the durable submission record carries the WHOLE config
            # (``_trace`` included): a takeover re-arms the same
            # submission from exactly this entry. A drop here means the
            # lease lapsed since the command gate — acking anyway would
            # hand the client an acked job NO successor can ever replay
            # (the acked-then-lost hole), so unwind and refuse instead.
            if not self._ha_append("submission", job_id=config.job_id,
                                   config=config.to_dict()):
                with self._lock:
                    self._jobs.pop(config.job_id, None)
                if not self._ha_leader_ok():
                    raise NotLeader(
                        f"submission {config.job_id} not durable: lease "
                        "lapsed (deposed)")
                # the log disk refused the record (ENOSPC/EIO): acking
                # anyway would be the acked-then-lost hole — refuse with
                # a retryable error; the client's bounded retry succeeds
                # once the store heals
                raise RuntimeError(
                    f"submission {config.job_id} not durable: log "
                    "append failed (sick log store); retry")
            jr.future.add_done_callback(
                lambda f, j=config.job_id: self._ha_record_done(j, f))
        self._scheduler.on_job_arrival(config)
        return jr.future

    def _launch(self, config: JobConfig, executor_ids: List[str]) -> None:
        """Scheduler-chosen launch: dispatch the job on a thread (the
        JobDispatcher.executeJob flow)."""
        t = threading.Thread(
            target=self._dispatch, args=(config, executor_ids), name=f"dispatch-{config.job_id}"
        )
        t.daemon = True
        with self._lock:
            # prune finished threads so a long-lived server doesn't retain
            # one dead Thread per job ever dispatched
            self._dispatch_threads = [x for x in self._dispatch_threads if x.is_alive()]
            self._dispatch_threads.append(t)
        t.start()

    def _trace_parent_of(self, config: JobConfig) -> Optional[SpanContext]:
        """Explicit re-parent target for a span opened on a fresh thread:
        the submission's wire context — UNLESS an ambient span already
        carries the trace (nested dispatch legs must nest, not re-root)."""
        if current_span() is not None:
            return None
        return SpanContext.from_wire(config.user.get("_trace"))

    def _dispatch(self, config: JobConfig, executor_ids: List[str]) -> None:
        with trace_span(
            "jobserver.dispatch",
            parent=self._trace_parent_of(config),
            job_id=config.job_id,
            executors=len(executor_ids),
        ):
            self._dispatch_job(config, executor_ids)

    def _dispatch_job(self, config: JobConfig, executor_ids: List[str]) -> None:
        jr = self._jobs[config.job_id]
        jlog = job_logger(config.job_id)
        jlog.info("dispatched on executors %s", executor_ids)
        from harmony_tpu.jobserver import elastic as _el

        self._ha_append("dispatch", job_id=config.job_id,
                        executors=list(executor_ids),
                        attempt=_el.attempt_of(config))
        t0 = time.monotonic()
        entity = None
        try:
            # build_entity inside the try: an unknown app_type or bad config
            # must resolve the future (else callers hang) and must still run
            # scheduler.on_job_finish (else FIFO wedges permanently).
            entity = build_entity(
                config,
                global_taskunit=self.global_taskunit,
                local_taskunit=self.local_taskunit,
                metric_sink=self._on_metric,
                chkp_root=self._chkp_root,
                metric_manager=self.metrics,
                **self._entity_extras(config, executor_ids),
            )
            with self._lock:
                self._entities[config.job_id] = entity
            entity.setup(self.master, executor_ids)
            result = entity.run()
            # Register the job's deferred model evaluation BEFORE cleanup
            # drops its tables — the eval replays checkpoints from disk at
            # shutdown, so it needs only the closure, not the tables.
            deferred = entity.deferred_evaluation()
            if deferred is not None:
                with self._lock:
                    self._deferred_evals[config.job_id] = deferred
            entity.cleanup()
            jlog.info("finished in %.1fs", time.monotonic() - t0)
            jr.future.set_result(result)
        except BaseException as e:  # noqa: BLE001 - delivered via future
            jlog.error("failed after %.1fs: %s: %s",
                       time.monotonic() - t0, type(e).__name__, e)
            if entity is not None:
                try:
                    entity.cleanup()
                except Exception:
                    pass
            jr.future.set_exception(e)
        finally:
            with self._lock:
                self._entities.pop(config.job_id, None)
            self._scheduler.on_job_finish(config.job_id)
            if _el.attempt_of(config) == 0 and not config.user.get(
                    "elastic_shrink"):
                # non-elastic submissions consume no reacquire: drop any
                # policy pin so it cannot leak to a reused job id (the
                # elastic loop clears its own at submission end — a pin
                # must survive the per-attempt finish that precedes its
                # consuming reacquire)
                try:
                    self._scheduler.plan_grant(config.job_id, None)
                except Exception:
                    pass

    def _entity_extras(self, config: JobConfig,
                       executor_ids: List[str]) -> Dict[str, Any]:
        """Subclass hook: extra build_entity kwargs (the pod server wires
        its plan channel for multi-process grants here)."""
        return {}

    def _scrape_targets(self) -> Dict[str, Any]:
        """History-scraper target provider: this process's own registry
        (sampled in-process — the leader pays no HTTP for itself) plus
        any ``HARMONY_OBS_SCRAPE_TARGETS`` extras (standalone inputsvc
        workers). The pod server adds follower exporters discovered
        from the heartbeat plumbing."""
        from harmony_tpu.metrics.registry import get_registry

        targets: Dict[str, Any] = {"leader": get_registry().expose}
        targets.update(extra_targets())
        if self.overload.degraded():
            # degraded fidelity: sample a rotating subset per cycle
            # (full coverage over a few cycles) instead of missing the
            # scrape period on every cycle. The leader's own in-process
            # registry is free and never rotated out.
            keep = self.overload.plan_subset(
                list(targets), plan="scrape", keep=("leader",))
            targets = {k: v for k, v in targets.items() if k in keep}
        return targets

    def _on_scrape_cycle(self) -> None:
        """After every history-scraper poll: the doctor evaluates its
        rules, then the policy engine (throttled to its own period)
        replans off the fresh verdicts — sensor before actuator, every
        cycle, both contained (a broken one must not stop the other).

        This is also the overload detector's telemetry feed: each
        stage's wall time is compared to the scrape period, and under
        degradation the doctor/policy evaluate only the rotating tenant
        subset with fresh samples (jobserver/overload.py)."""
        ov = self.overload
        period = self._history_scraper.period
        st = self._history_scraper.stats()
        ov.note_cycle("scrape",
                      float(st.get("last_cycle_ms") or 0.0) / 1000.0,
                      period)
        jobs = None
        if ov.degraded():
            try:
                jobs = set(ov.plan_subset(
                    [str(j) for j in self.metrics.tenant_ledger()],
                    plan="tenants"))
            except Exception:
                jobs = None
        t0 = time.monotonic()
        try:
            self.doctor.diagnose(jobs=jobs)
        except Exception:
            pass
        ov.note_cycle("diagnose", time.monotonic() - t0, period)
        t0 = time.monotonic()
        try:
            if ov.shedding():
                # the planner is pure fidelity: at the bottom rung it
                # sheds whole evaluations, not just tenants
                ov.count_shed("policy_skip")
            else:
                self.policy.maybe_evaluate(jobs=jobs)
        except Exception:
            pass
        ov.note_cycle("plan", time.monotonic() - t0, period)
        t0 = time.monotonic()
        try:
            self.incidents.correlate()
        except Exception:
            pass
        ov.note_cycle("correlate", time.monotonic() - t0, period)
        ov.step()

    def _policy_tenants(self) -> Dict[str, Dict[str, Any]]:
        """Policy-engine actuator view: the running tenants whose
        placement CAN be replanned (elastic attempts with a fence
        channel). The plain server has none — the pod server overrides
        with its elastic-active bookkeeping."""
        return {}

    def _policy_fence(self, job_id: str, kind: str) -> Optional[int]:
        """Policy-engine actuator: schedule a lockstep elastic fence on
        a running attempt. No fence channel on the plain server —
        actions stay advisory here."""
        return None

    def _post_policy(self, action: Dict[str, Any]) -> None:
        """Policy sink: tee every recorded action to the dashboard as a
        kind="policy" row (same best-effort contract as metric posts)."""
        if self._dashboard is not None:
            try:
                self._dashboard.post(str(action.get("job")), "policy",
                                     dict(action))
            except Exception:
                pass  # dashboard posts are best-effort by contract

    def _post_diagnosis(self, diag) -> None:
        """Doctor sink: tee every fresh diagnosis to the dashboard as a
        kind="diagnosis" row (same best-effort contract as metric
        posts) so the history panel can overlay verdicts on series."""
        if self._dashboard is not None:
            try:
                self._dashboard.post(diag.subject, "diagnosis",
                                     diag.to_dict())
            except Exception:
                pass  # dashboard posts are best-effort by contract

    def _post_incident(self, incident: Dict[str, Any]) -> None:
        """Incident-engine sink: tee every lifecycle transition to the
        dashboard as a kind="incident" row (same best-effort contract
        as metric posts) so the /incidents panel can render timelines."""
        if self._dashboard is not None:
            try:
                self._dashboard.post(str(incident.get("subject")),
                                     "incident", dict(incident))
            except Exception:
                pass  # dashboard posts are best-effort by contract

    def _ensure_input_service(self) -> None:
        """Start the embedded input service + its autoscaler once. A
        configured HARMONY_INPUT_SERVICE_ADDR means a standalone service
        process owns the role — workers will use it directly and the
        jobserver starts nothing."""
        import os

        from harmony_tpu import inputsvc

        if os.environ.get("HARMONY_INPUT_SERVICE_ADDR"):
            return
        with self._lock:
            if self.input_service is not None:
                return
            svc = inputsvc.InputService()
            port = svc.start()
            inputsvc.set_default_endpoint(("127.0.0.1", port))
            metrics = self.metrics

            def wait_frac() -> "float | None":
                rows = metrics.tenant_ledger()
                fr = [r.get("input_wait_frac") for r in rows.values()
                      if r.get("input_wait_frac") is not None]
                return sum(fr) / len(fr) if fr else None

            def straggler() -> "float | None":
                reps = metrics.straggler_report()
                ratios = [r["ratio"] for r in reps.values()]
                return max(ratios) if ratios else None

            # the autoscaler shares the POLICY engine's rate-limit gate:
            # input-worker scaling and device packing both key off the
            # input-wait signal, and a shared cooldown on that signal is
            # what keeps them from fighting over it
            scaler = inputsvc.InputAutoscaler(svc, wait_frac, straggler,
                                              gate=self.policy.gate)
            scaler.start()
            self.input_service = svc
            self._input_autoscaler = scaler
        server_log.info("input service up on port %d (%d workers)",
                        port, svc.workers)

    def _stop_input_service(self) -> None:
        with self._lock:
            svc, self.input_service = self.input_service, None
            scaler, self._input_autoscaler = self._input_autoscaler, None
        if scaler is not None:
            scaler.stop()
        if svc is not None:
            from harmony_tpu import inputsvc

            inputsvc.set_default_endpoint(None)
            svc.stop()

    def _ensure_serving(self):
        """Start the embedded serving endpoint once (first SERVING
        command) and return it. Live lookups resolve through
        ``_entities`` — the same handle the trainers update — and
        pinned lookups through this server's checkpoint root; admission
        rides the shared overload monitor."""
        with self._lock:
            if self.serving is not None:
                return self.serving
            from harmony_tpu.serving import ServingEndpoint

            def live_table(job_id: str):
                with self._lock:
                    entity = self._entities.get(job_id)
                handle = (getattr(entity, "table_handle", None)
                          if entity is not None else None)
                return handle.table if handle is not None else None

            svc = ServingEndpoint(
                table_fn=live_table,
                chkp_root=self._chkp_root,
                overload=self.overload,
            )
            port = svc.start()
            self.serving = svc
        server_log.info("serving endpoint up on port %d", port)
        return svc

    def _stop_serving(self) -> None:
        with self._lock:
            svc, self.serving = self.serving, None
        if svc is not None:
            svc.stop()

    def running_jobs(self) -> List[str]:
        with self._lock:
            return [j for j, r in self._jobs.items() if not r.future.done()]

    def _status(self) -> Dict[str, Any]:
        """STATUS reply body (subclasses extend, e.g. pod health)."""
        from harmony_tpu.jobserver import joblog

        from harmony_tpu.tracing import flight

        # ONE straggler walk per STATUS: the report, the ledger join
        # and the phase-budget analysis all consume the same figures
        stragglers = self.metrics.straggler_report()
        return {
            "ok": True,
            "state": self.state,
            "running": self.running_jobs(),
            "evaluated": sorted(self.eval_results),
            # recovery observability: fault-injection fires + transport/
            # checkpoint retry counters + isolated-worker respawns for
            # THIS process, and the structured per-job recovery events
            # (shrink/re-grow/confinement/rehabilitation)
            "fault_counters": self.metrics.fault_counters(),
            "job_events": joblog.job_events(),
            # telemetry plane: per-job straggler attribution from the
            # step-time records, this process's flight-recorder dumps
            # (path + correlated trace ids), and where /metrics lives
            "stragglers": stragglers,
            # per-tenant device cost accounting (metrics/accounting.py):
            # MFU, device-seconds, resident HBM, input-wait, SLO
            # attainment per job@attempt — what `obs top` renders
            "tenants": self.metrics.tenant_ledger(stragglers=stragglers),
            # step-phase time budget + critical-path attribution
            # (metrics/phases.py + critpath.py): per-tenant phase
            # seconds/fractions, bound classification, and per-epoch
            # gating worker+phase — what `obs critpath` renders
            "phase_budget": self.metrics.phase_budget(
                stragglers=stragglers),
            # newest sampled device-profile capture on THIS process's
            # disk (HARMONY_PROFILE_DIR), if the sampler ever ran —
            # until now xplane dumps landed and nothing referenced them
            "profile_capture": flight.profile_capture_path(),
            "flight_records": flight.get_recorder().records(),
            "metrics_port": (self.metrics_exporter.port
                             if self.metrics_exporter is not None else None),
            # telemetry history + doctor (metrics/history.py + doctor.py):
            # store/scraper shape and the newest structured diagnoses —
            # what `harmony-tpu obs doctor` renders
            "history": {**self.history.stats(),
                        "scraper": self._history_scraper.stats()},
            "diagnoses": self.doctor.recent(),
            # disaggregated input service (harmony_tpu/inputsvc): port,
            # worker slots, per-tenant queue traffic, cache hit/byte
            # stats and autoscaler events — None when not running
            "input_service": (self.input_service.stats()
                              if self.input_service is not None else None),
            # serving plane (harmony_tpu/serving): port, per-tenant
            # qps/latency, batch occupancy and cache hit/byte stats —
            # None until the first SERVING command starts it
            "serving": (self.serving.stats()
                        if self.serving is not None else None),
            # control-plane HA (jobserver/ha.py): role, leader epoch,
            # durable-log/lease/replication shape and recent takeovers —
            # {"enabled": False} outside an HA deployment
            "ha": self._ha_status(),
            # device policy engine (jobserver/policy.py): mode, the last
            # computed plan (candidates + why each was or wasn't acted
            # on), recent actions, and the rate-limit gate's state —
            # what `harmony-tpu obs plan` renders
            "policy": self.policy.status(),
            # control-plane overload (jobserver/overload.py): ladder
            # level, queue fill/lag, shed counters and the recovery
            # gate — the operator's "is fidelity degraded, and why"
            "overload": self.overload.status(),
            # incident correlation (metrics/incidents.py): open/
            # mitigating/resolved counts, MTTR, and the newest causal
            # chains — what `harmony-tpu obs incidents` renders
            "incidents": self.incidents.status(),
        }

    # -- TCP command endpoint (ref: CommandListener) ---------------------

    #: byte cap on ONE command message — the same fix class as the
    #: scraper's bounded read (metrics/history.py _read_bounded): a
    #: client streaming forever must cost a bounded buffer, not RSS
    _MAX_CMD_BYTES = 16 << 20

    def serve_tcp(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Listen on ``host`` (default localhost — the single-machine
        contract; an HA control plane whose clients live on other hosts
        binds its advertised interface, cli --ha-bind); returns the
        bound port. Wire format: one JSON object per connection:
        {"command": "SUBMIT", "conf": <JobConfig>} or
        {"command": "SHUTDOWN"}; reply is one JSON object.

        Bounded command plane (jobserver/overload.py): the accept loop
        feeds a bounded queue drained by a FIXED worker pool — never a
        thread per connection (that was the wedge under submit storms:
        thousands of connections, thousands of threads, then the GIL
        and RSS fall over together). A full queue answers BUSY
        {retry_after_ms} right at accept; admission for SUBMIT is
        checked again, against dispatch in-flight, before anything
        durable happens."""
        from harmony_tpu import faults
        from harmony_tpu.jobserver import overload as _ov

        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        sock.listen(64)
        cap = _ov.cmd_queue_cap()
        q: "_queue.Queue" = _queue.Queue(maxsize=cap)
        workers: List[threading.Thread] = []
        for i in range(_ov.cmd_workers()):
            t = threading.Thread(target=self._cmd_worker, args=(q, cap),
                                 daemon=True, name=f"jobserver-cmd-{i}")
            t.start()
            workers.append(t)
        with self._lock:
            self._tcp_sock = sock
            self._cmd_queue = q
            self._cmd_workers = workers
            self._cmd_queue_cap = cap
        self.port = sock.getsockname()[1]

        def loop() -> None:
            while True:
                try:
                    conn, _ = sock.accept()
                except OSError:
                    return  # socket closed
                if faults.armed():
                    try:
                        faults.site("server.accept", depth=q.qsize())
                    except Exception:
                        # an injected accept fault drops THIS connection
                        # (a flaky NIC/kernel accept path); the loop and
                        # the queued work are untouched
                        try:
                            conn.close()
                        except OSError:
                            pass
                        continue
                try:
                    q.put_nowait((conn, time.monotonic()))
                except _queue.Full:
                    # shed at the door, loudly: a structured BUSY beats
                    # an accepted-then-starved connection every time
                    self.overload.note_queue(q.qsize(), cap)
                    self.overload.count_shed("accept_shed")
                    self._send_busy(conn, self.overload.retry_after_ms())
                    self.overload.step()

        self._tcp_thread = threading.Thread(target=loop, daemon=True, name="jobserver-tcp")
        self._tcp_thread.start()
        return self.port

    def _send_busy(self, conn: socket.socket, retry_after_ms: int) -> None:
        """Best-effort BUSY reply on a connection being shed (bounded —
        the accept loop must never block on a slow shed client)."""
        reply = {"ok": False, "busy": True,
                 "retry_after_ms": int(retry_after_ms),
                 "error": "BUSY: control plane overloaded"}
        try:
            conn.settimeout(1.0)
            conn.sendall((json.dumps(reply) + "\n").encode())
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _cmd_worker(self, q: "_queue.Queue", cap: int) -> None:
        """One fixed-pool worker: drain the accept queue forever (a
        None sentinel stops it). Queue lag — how long the connection
        waited for a worker — is the overload detector's primary
        command-plane signal."""
        while True:
            item = q.get()
            if item is None:
                return
            conn, enq_t = item
            lag = time.monotonic() - enq_t
            self.overload.note_queue(q.qsize(), cap, lag_sec=lag)
            self.overload.step()
            try:
                self._handle_conn(conn)
            except Exception:  # noqa: BLE001 - a handler bug must not
                pass           # kill the pool worker

    def _read_command(self, conn: socket.socket,
                      deadline: float) -> bytes:
        """Bounded read of one newline-terminated command: capped in
        BYTES and WALL CLOCK (not per-recv — a trickling client used to
        reset a 30s timeout on every byte and hold its thread forever;
        same fix class as the PR-11 scraper hardening)."""
        data = b""
        while not data.endswith(b"\n"):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.overload.count_shed("slowloris_evict")
                raise TimeoutError(
                    "command read exceeded its wall-clock deadline "
                    "(slow client evicted)")
            conn.settimeout(min(5.0, remaining))
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue  # loop re-checks the WALL deadline
            if not chunk:
                break
            data += chunk
            if len(data) > self._MAX_CMD_BYTES:
                self.overload.count_shed("oversize_evict")
                raise ValueError(
                    f"command exceeds {self._MAX_CMD_BYTES} byte cap")
        return data

    def _handle_conn(self, conn: socket.socket) -> None:
        from harmony_tpu import faults

        from harmony_tpu.jobserver import overload as _ov

        deadline = time.monotonic() + _ov.cmd_deadline_sec()
        # The error reply MUST go out before `with conn` closes the socket —
        # sending after close silently drops it and the client sees bare EOF.
        with conn:
            try:
                data = self._read_command(conn, deadline)
                msg = json.loads(data.decode())
                cmd = msg.get("command")
                if faults.armed():
                    # raises = an injected command-path failure; it
                    # surfaces to the client as a structured error reply
                    faults.site("server.command", cmd=str(cmd))
                if (cmd in ("SUBMIT", "POD_RESHARD", "WAIT", "SERVING")
                        and not self._ha_leader_ok()):
                    # deposed leader: every mutating/authoritative
                    # command redirects — a client following the lease
                    # holder's advertised address lands on the successor
                    reply = self._not_leader_reply()
                elif cmd == "SUBMIT":
                    # Admission BEFORE anything durable: a rejected
                    # submission left no trace (no registry entry, no
                    # joblog append), an admitted one proceeds into
                    # submit()'s durable path — accepted-then-shed is
                    # structurally impossible.
                    with self._lock:
                        q = self._cmd_queue
                    retry_ms = self.overload.admit_submit(
                        queue_depth=(q.qsize() if q is not None else 0),
                        queue_cap=(self._cmd_queue_cap or 1),
                        inflight=len(self.running_jobs()))
                    if retry_ms is not None:
                        reply = {"ok": False, "busy": True,
                                 "retry_after_ms": retry_ms,
                                 "error": "BUSY: control plane "
                                          "overloaded; retry after "
                                          f"{retry_ms}ms"}
                    else:
                        config = ConfigBase.from_dict(msg["conf"])
                        # the client's span context (client.py sends it
                        # beside the config): ride it inside the config
                        # so the whole dispatch chain re-parents onto
                        # the CLI's trace
                        wire = msg.get("trace")
                        if wire and "_trace" not in config.user:
                            config.user["_trace"] = dict(wire)
                        try:
                            with trace_span(
                                "jobserver.submit",
                                parent=SpanContext.from_wire(
                                    config.user.get("_trace")),
                                job_id=config.job_id,
                            ):
                                self.submit(config)
                            reply = {"ok": True, "job_id": config.job_id}
                        except NotLeader:
                            # deposed BETWEEN the gate check and the
                            # durable append: the submission was unwound,
                            # so redirect instead of acking a job no
                            # successor can replay
                            reply = self._not_leader_reply()
                elif cmd == "STATUS":
                    reply = self._status()
                elif cmd == "WAIT":
                    # bounded wait on a submission's result — the
                    # failover client's way to follow ONE submission
                    # across a leader change (the successor re-arms it
                    # under the same job id and resolves a fresh future)
                    job_id = str(msg.get("job_id"))
                    # the future poll is also capped by the command
                    # deadline: a WAIT occupies one fixed-pool worker,
                    # and clients poll in a loop anyway (wait_result)
                    timeout = min(float(msg.get("timeout", 30.0)), 300.0,
                                  max(0.5, deadline - time.monotonic()))
                    with self._lock:
                        jr = self._jobs.get(job_id)
                    if jr is None:
                        reply = {"ok": False, "known": False,
                                 "error": f"unknown job {job_id!r}"}
                    else:
                        try:
                            result = jr.future.result(timeout=timeout)
                            reply = {"ok": True, "done": True,
                                     "result": _json_sanitize(result)}
                        except (TimeoutError, FuturesTimeoutError):
                            reply = {"ok": True, "done": False,
                                     "running": job_id in
                                     self.running_jobs()}
                        except BaseException as e:  # noqa: BLE001
                            reply = {"ok": False, "known": True,
                                     "done": True,
                                     "error": f"{type(e).__name__}: {e}"}
                elif cmd == "POD_RESHARD":
                    # operator-initiated live migration of a running pod
                    # job (PodJobServer.schedule_pod_reshard; plain
                    # servers reject — the attribute is pod-only)
                    fn = getattr(self, "schedule_pod_reshard", None)
                    if fn is None:
                        reply = {"ok": False,
                                 "error": "not a pod server"}
                    else:
                        fn(job_id=str(msg["job_id"]), src=str(msg["src"]),
                           dst=str(msg["dst"]),
                           num_blocks=int(msg["num_blocks"]),
                           epoch=int(msg["epoch"]))
                        reply = {"ok": True}
                elif cmd == "SERVING":
                    # serving-endpoint discovery (harmony_tpu/serving):
                    # starts the data plane on demand and answers its
                    # address. Leader-gated above: only the replica that
                    # owns live tables (and re-arms the checkpoint
                    # chains) may advertise itself to readers, so a
                    # takeover re-routes every ServingClient through
                    # the same NOT_LEADER walk as submissions.
                    svc = self._ensure_serving()
                    reply = {"ok": True, "port": svc.port,
                             "host": svc.address[0]}
                elif cmd == "SHUTDOWN":
                    threading.Thread(target=self.shutdown, daemon=True).start()
                    reply = {"ok": True}
                else:
                    reply = {"ok": False, "error": f"unknown command {cmd!r}"}
            except Exception as e:  # noqa: BLE001 - reported to the client
                reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                conn.sendall((json.dumps(reply) + "\n").encode())
            except OSError:
                pass  # client went away; nothing to tell it

    def _stop_tcp(self) -> None:
        # under the lock: shutdown() can be invoked from a TCP handler
        # thread, and two concurrent SHUTDOWNs racing this check-close-
        # clear sequence could close-then-read a None socket
        with self._lock:
            sock, self._tcp_sock = self._tcp_sock, None
            q, self._cmd_queue = self._cmd_queue, None
            workers, self._cmd_workers = self._cmd_workers, []
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if q is not None:
            # drain queued (never-served) connections so their clients
            # see EOF now, then stop the pool with one sentinel each
            while True:
                try:
                    item = q.get_nowait()
                except _queue.Empty:
                    break
                if item is not None:
                    try:
                        item[0].close()
                    except OSError:
                        pass
            for _ in workers:
                try:
                    q.put(None, timeout=1.0)
                except _queue.Full:
                    break  # workers are daemons; leak rather than hang
            # a worker mid-WAIT legitimately holds its slot up to the
            # command deadline — don't stall shutdown on it
            for t in workers:
                if t is not threading.current_thread():
                    t.join(timeout=0.5)
