"""Multi-host JobServer — the driver/evaluator split over real processes.

The reference's JobServer is a driver PROCESS coordinating remote evaluator
JVMs (ref: jobserver/src/main/java/edu/snu/cay/jobserver/driver/
JobServerDriver.java:149-163, ResourcePool.java:73-81). The TPU-pod
equivalent keeps the same split with JAX's multi-controller SPMD model:

  * every host process joins one ``jax.distributed`` runtime
    (parallel/multihost.py), after which ``jax.devices()`` is the GLOBAL
    chip list on all of them;
  * process 0 runs the :class:`PodJobServer` — the ordinary JobServer
    (scheduling, registry, TCP submit endpoint) plus a pod control plane;
  * every other process runs a :class:`PodFollower` loop.

Control plane (DCN, JSON-over-TCP — same framing as client.py): followers
JOIN the leader; for each dispatched job the leader sends RUN_JOB to the
followers whose processes hold devices of the job's executor grant, every
participating process builds the SAME JobEntity and runs it, and the jitted
train steps inside are mesh-wide SPMD programs — their XLA collectives
(ICI/DCN) are the data plane and the de-facto barrier, exactly the
reference's msg-plus-collective split (SURVEY.md §5.8). At job end
participants report JOB_DONE with their local worker metrics, which the
leader records per process id — the cross-process metric flow the reference
routes through its MetricManager msg senders.

Concurrent multi-tenancy (the reference's defining property —
SchedulerImpl.java:28-66 runs every job on all executors, the
GlobalTaskUnitScheduler interleaves them): the hazard is that a process's
per-device XLA streams execute in enqueue order, and a multi-process
program blocks its process inside collectives until every participant
arrives — so two multi-process jobs sharing processes that enqueue in
different orders on different hosts deadlock the pod (a distributed
lock-order inversion). Two mechanisms make tenancy safe:

  * the CROSS-JOB UNIT PROTOCOL (runtime/podunits.py): every multi-process
    dolphin AND pregel job wraps its global-dispatch regions in
    leader-granted units; the leader's arbiter never leaves units of two
    process-overlapping jobs outstanding at once, so every process's
    cross-job enqueue order IS the grant order. SHARE-ALL grants (every
    job on all executors — the reference's default) therefore run truly
    concurrently, interleaved in one pod-wide weighted-fair order;
  * the admission rule in ``_dispatch`` for everything else: disjoint
    process sets are always concurrent; single-process jobs are always
    concurrent (their shared-device pairs live in one process, whose
    dispatch lock enqueues each program atomically — no pair can invert);
    a multi-process job OUTSIDE the unit protocol (``user.pod_isolated``
    opt-outs) serializes against any other overlapping multi-process
    job, and a job waiting on admission holds a FIFO ticket reserving
    its processes against later arrivals so a stream of small jobs
    cannot starve it.

The ``pod_carve`` scheduler (scheduler.ProcessCarveScheduler) still
produces process-disjoint grants for tenants that want isolation (no
cross-job unit round-trips at all).

Determinism contract (what makes per-job lockstep correct): entity
construction is a pure function of the JobConfig, executor ids are
allocated by a fresh per-process counter in identical order, and
synthetic/file data loading is seeded — so all of a job's participants
issue the same global computations in the same order.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from harmony_tpu.config.base import ConfigBase
from harmony_tpu.config.params import JobConfig
from harmony_tpu.jobserver import elastic as _elastic
from harmony_tpu.jobserver.joblog import job_logger, server_log
from harmony_tpu.jobserver.scheduler import ProcessCarveScheduler
from harmony_tpu.jobserver.server import JobResult, JobServer, _json_sanitize
from harmony_tpu.runtime.podunits import (
    FollowerUnits,
    PodUnitArbiter,
    follower_client,
    leader_client,
)
from harmony_tpu.tracing.span import SpanContext, trace_span, wire_context


def _send(sock: socket.socket, msg: Dict[str, Any]) -> None:
    sock.sendall((json.dumps(msg) + "\n").encode())


def _recv(f) -> Optional[Dict[str, Any]]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


# the chief-report path shares the WAIT reply's best-effort JSON
# projection (one implementation; server.py owns it)


class PodJobServer(JobServer):
    """JobServer on process 0 of a pod: adds the follower control plane."""

    def __init__(self, *args, num_followers: int = 0, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._num_followers = num_followers
        self._pod_sock: Optional[socket.socket] = None
        self._followers: Dict[int, Any] = {}  # pid -> (sock, reader file)
        self._send_locks: Dict[int, threading.Lock] = {}
        # One condition guards all pod state: active job->process sets
        # (admission), the report buffer the reader threads fill, dead
        # followers, and the broken flag.
        self._pod_cond = threading.Condition()
        #: job_id -> (process set, pod_ordered) — pod_ordered jobs run the
        #: cross-job unit protocol and may overlap other pod_ordered jobs
        self._active_procs: Dict[str, Tuple[frozenset, bool]] = {}
        # FIFO admission tickets: a waiting job reserves its processes
        # against LATER arrivals (ticketless candidates rank newest) so a
        # stream of small jobs cannot starve a pod-spanning one
        # (job_id -> (ticket, procs, pod_ordered) while waiting)
        self._admission_ticket = 0
        self._admission_waiting: Dict[str, Tuple[int, frozenset, bool]] = {}
        # Cross-job dispatch-order arbiter (share-all multi-tenancy):
        # see runtime/podunits.py
        self.pod_units = PodUnitArbiter(send_to=self._send_to)
        # Liveness, not duration: followers HEARTBEAT every few seconds,
        # and the leader declares a follower infra-dead only on heartbeat
        # SILENCE — never because a healthy job ran long (real training
        # runs hours; the reference's driver waits on tasklet status
        # indefinitely, TaskletRepresenter.java).
        self.hb_timeout = float(os.environ.get("HARMONY_POD_HB_TIMEOUT",
                                               "60"))
        self._last_seen: Dict[int, float] = {}
        #: pid -> last HEARTBEAT (the beacon specifically, not any
        #: traffic): confinement is conservative (any traffic counts as
        #: liveness), REHABILITATION is strict — a confined follower
        #: answering a leader-solicited query is reachable, but only its
        #: own resumed beacon proves the silence is actually over
        #: (otherwise the fence's progress query would instantly
        #: "rehabilitate" a mute follower and the pod would flap)
        self._last_beat: Dict[int, float] = {}
        #: pid -> set of job ids the follower's latest heartbeat listed —
        #: catches a job thread that died without ever reporting
        self._hb_jobs: Dict[int, set] = {}
        #: pid -> the /metrics exporter port the follower's heartbeat
        #: advertises (history-scraper target discovery); absent when
        #: the follower runs without HARMONY_METRICS_PORT
        self._hb_metrics_ports: Dict[int, int] = {}
        #: pid -> the peer address the follower connected from — the
        #: host half of its scrape target
        self._follower_hosts: Dict[int, str] = {}
        # Failure confinement (beyond the reference's fail-fast stubs,
        # JobServerDriver.java:271-298): a follower death marks only the
        # dead process AND processes sharing a running job with it as
        # unusable ("partial" poison scope) — jobs wholly on other
        # processes keep dispatching, and auto_resume-flagged jobs
        # resubmit from their checkpoint chains onto survivors. Non-death
        # poisons (partial broadcasts) stay TOTAL.
        self._unusable_procs: set = set()
        self._poison_scope: Optional[str] = None  # "partial" | "total"
        #: pids confined by heartbeat SILENCE (the process may well be
        #: alive — a partition, a wedged beacon): the pod monitor both
        #: confines on staleness and REHABILITATES when beats resume,
        #: the in-place half of elastic re-grow
        self._silenced: set = set()
        #: job_id -> live elastic attempt bookkeeping ({"attempt",
        #: "procs", "original_procs", "config"}) — what fence
        #: scheduling and re-grow triggers read
        self._elastic_active: Dict[str, Dict[str, Any]] = {}
        #: recent elastic recovery events (bounded; status surface)
        self.elastic_events: List[Dict[str, Any]] = []
        self._monitor_stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._late_join_thread: Optional[threading.Thread] = None
        #: pids reinstated after death/silence (observability + tests)
        self.reinstated: List[int] = []
        #: jobs whose FAILURE was infra-observed (a participant died or
        #: went silent DURING the job) — the auto-resume eligibility
        #: evidence; a job failing on its own terms never lands here
        self._infra_failed: set = set()
        #: job ids this server auto-resumed (observability + tests)
        self.auto_resumed: List[str] = []
        self._reports: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._dead_followers: set = set()
        self._readers: List[threading.Thread] = []
        self._pod_closing = False
        # A partially-delivered RUN_JOB leaves the followers that DID
        # receive it blocked in global collectives (XLA collectives do not
        # time out); no job overlapping those processes can run. The flag
        # fails subsequent pod dispatches fast instead of hanging them.
        self._pod_broken: Optional[str] = None
        #: job_id -> {pid: follower JOB_DONE payload}
        self.pod_reports: Dict[str, Dict[int, Dict[str, Any]]] = {}
        #: job_id -> (dispatch start, dispatch end) monotonic times — the
        #: concurrency evidence (overlapping walls = jobs truly overlapped)
        self.job_walls: Dict[str, Tuple[float, float]] = {}
        # Remote deferred evals: job_id -> chief pid holding the closure
        # (filled from JOB_DONE's has_deferred_eval), and the EVAL_DONE
        # results the readers collect during shutdown.
        self._remote_evals: Dict[str, int] = {}
        self._remote_eval_results: Dict[str, Any] = {}
        # job_id -> (follower participants, effective workers): what
        # schedule_pod_reshard needs to target PLAN broadcasts
        self._job_info: Dict[str, Tuple[List[int], int]] = {}
        # retained past job end (deferred evals run at shutdown):
        # job_id -> follower participants for the collective eval
        self._eval_participants: Dict[str, List[int]] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        super().start()
        if isinstance(self._scheduler, ProcessCarveScheduler):
            self._scheduler.set_process_map({
                eid: self.master.executor(eid).device.process_index
                for eid in self.master.executor_ids()
            })

    # -- follower management --------------------------------------------

    def serve_pod(self, port: int = 0, join_timeout: float = 300.0) -> int:
        """Listen for follower JOINs; blocks until all ``num_followers``
        processes have joined (startup is a pod-wide barrier — dispatching
        before the pod is whole would hang the first collective anyway).
        Once whole, one reader thread per follower demultiplexes its
        JOB_DONE stream into the report buffer — concurrent jobs each wait
        only on their own participants' reports."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("0.0.0.0", port))
        sock.listen(16)
        self._pod_sock = sock
        bound = sock.getsockname()[1]
        sock.settimeout(join_timeout)
        while len(self._followers) < self._num_followers:
            try:
                conn, addr = sock.accept()
            except socket.timeout:
                raise TimeoutError(
                    f"pod join: {len(self._followers)}/{self._num_followers} "
                    f"followers after {join_timeout}s"
                )
            pid, f = self._read_join(conn)
            if pid is None:
                continue
            # under the pod cond even though readers start below: the
            # late-join acceptor and monitor mutate these same maps from
            # their threads, and every mutation site holds the lock (the
            # thread-shared-state lint pins this)
            with self._pod_cond:
                self._followers[pid] = (conn, f)
                self._send_locks[pid] = threading.Lock()
                self._last_seen[pid] = time.monotonic()
                self._follower_hosts[pid] = addr[0]
            server_log.info("pod follower %d joined from %s", pid, addr)
        for pid, (conn, f) in sorted(self._followers.items()):
            t = threading.Thread(
                target=self._reader_loop, args=(pid, f), daemon=True,
                name=f"pod-reader-{pid}",
            )
            t.start()
            self._readers.append(t)
        # Active liveness: heartbeat staleness is now noticed WHENEVER it
        # happens (a report wait used to be the only observer — a silent
        # follower under a leader-local job went undetected until job
        # end), and resumed beats from a silence-confined follower
        # rehabilitate it (the elastic re-grow trigger).
        self._monitor_thread = threading.Thread(
            target=self._pod_monitor, daemon=True, name="pod-monitor",
        )
        self._monitor_thread.start()
        # Replacement followers may JOIN at any time after bootstrap — a
        # restarted host, or a partition healing with a fresh process.
        sock.settimeout(1.0)
        self._late_join_thread = threading.Thread(
            target=self._accept_late_joins, daemon=True,
            name="pod-late-join",
        )
        self._late_join_thread.start()
        return bound

    @staticmethod
    def _read_join(conn: socket.socket) -> "Tuple[Optional[int], Any]":
        """One JOIN handshake on a fresh connection; (None, None) for
        garbage. accept()'d sockets are BLOCKING regardless of the
        listener's timeout: a connection that never sends JOIN (health
        check, scanner, crashed follower) must not hang the accept loop
        forever."""
        conn.settimeout(30.0)
        f = conn.makefile("r")
        try:
            hello = _recv(f)
            # garbage (an HTTP health check, a scanner) or a JOIN with
            # no pid must be dropped like silence, not crash the loop
            pid = int(hello["pid"]) if hello else None
        except (socket.timeout, OSError, ValueError, KeyError, TypeError):
            hello, pid = None, None
        if not hello or hello.get("cmd") != "JOIN" or pid is None:
            conn.close()
            return None, None
        conn.settimeout(None)  # the reader thread owns this socket now
        return pid, f

    def _accept_late_joins(self) -> None:
        """Post-bootstrap accept loop: a JOIN for a dead/confined pid is
        a REPLACEMENT follower (or the same host restarted) and gets
        reinstated; a JOIN for a live pid replaces its connection (the
        old one is stale — e.g. the follower reconnected after a
        partition its end diagnosed first)."""
        sock = self._pod_sock
        while True:
            with self._pod_cond:
                if self._pod_closing:
                    return
            try:
                conn, addr = sock.accept()
            except socket.timeout:
                continue
            except (OSError, AttributeError):
                return  # listener closed (shutdown)
            pid, f = self._read_join(conn)
            if pid is None:
                continue
            server_log.info("pod follower %d re-JOINed from %s", pid, addr)
            self._reinstate_follower(pid, conn, f)

    def _reinstate_follower(self, pid: int, conn: socket.socket, f) -> None:
        """Wire a replacement follower back into the pod: fresh reader,
        liveness state cleared, executors restored to the scheduler, and
        running shrunk elastic jobs offered a re-grow fence."""
        try:
            peer_host = conn.getpeername()[0]
        except OSError:
            peer_host = None
        with self._pod_cond:
            old = self._followers.pop(pid, None)
            self._followers[pid] = (conn, f)
            self._send_locks[pid] = threading.Lock()
            self._last_seen[pid] = time.monotonic()
            self._hb_jobs.pop(pid, None)
            # a replacement process re-advertises its exporter on its
            # next beat; the old port may belong to a dead process
            self._hb_metrics_ports.pop(pid, None)
            if peer_host is not None:
                self._follower_hosts[pid] = peer_host
            self._dead_followers.discard(pid)
            self._pod_cond.notify_all()
        if old is not None:
            try:
                old[0].close()
            except OSError:
                pass
        t = threading.Thread(
            target=self._reader_loop, args=(pid, f), daemon=True,
            name=f"pod-reader-{pid}",
        )
        t.start()
        self._readers.append(t)
        self.reinstated.append(pid)
        self.pod_units.proc_done(pid)  # stale DONE obligations die here
        self._rehabilitate(pid, reason="replacement JOIN")

    def _mark_broken(self, reason: str, scope: str = "total") -> None:
        """One poison path: record the reason and wake every pod waiter.
        TOTAL scope (protocol failures — partial broadcasts, eval
        divergence) additionally force-grants the unit arbiter and tells
        the followers' unit trackers (blocked threads proceed and fail
        through normal error paths instead of wedging). PARTIAL scope
        (a follower death whose damage is confined by _on_follower_death)
        keeps the arbiter intact — surviving overlapping tenants still
        need its ordering."""
        with self._pod_cond:
            if self._pod_broken is None:
                self._pod_broken = reason
                server_log.error("pod broken (%s): %s", scope, reason)
            if self._poison_scope != "total":
                self._poison_scope = scope
            total = self._poison_scope == "total"
            self._pod_cond.notify_all()
        if not total:
            return
        self.pod_units.poison()
        for pid in sorted(self._followers):
            try:
                self._send_to(pid, {"cmd": "TU_POISON"})
            except OSError:
                pass

    def _record_infra_failed_locked(self, job_id: str) -> None:
        """Record auto-resume evidence for ``job_id`` (caller holds
        _pod_cond). Trim BEFORE adding: evicting an arbitrary set element
        after the add could evict the id just recorded and silently lose
        the evidence (ids for jobs without auto_resume are never consumed
        by _maybe_auto_resume, so the set does grow on long-lived pods)."""
        while len(self._infra_failed) >= 1024:
            self._infra_failed.pop()
        self._infra_failed.add(job_id)

    def _on_follower_death(self, pid: int) -> None:
        """Confine the damage: the dead process — and every process
        sharing a RUNNING job with it (their threads may be wedged in
        collectives the dead devices will never join) — becomes unusable;
        its executors retire from future grants. Everything else stays
        schedulable, so surviving jobs keep running and flagged jobs can
        auto-resume."""
        with self._pod_cond:
            if pid in self._unusable_procs:
                return  # already confined (reader-EOF + report paths race)
            wedged = {pid}
            for jid, (ps, _) in self._active_procs.items():
                if pid in ps:
                    wedged |= ps
            self._unusable_procs |= wedged
        retired = [
            eid for eid in self.master.executor_ids()
            if self.master.executor(eid).device.process_index in wedged
        ]
        if retired:
            self._scheduler.retire(retired)
            server_log.warning(
                "retired executors %s (unusable processes %s)",
                retired, sorted(wedged),
            )
        # Black box for the death: the leader's recent spans/events around
        # the moment the follower vanished (tracing/flight.py). The ring
        # event is synchronous (cheap); the file dump runs on its own
        # thread — the death path feeds confinement and pod poisoning,
        # and must not stall on disk I/O (the ring snapshot is taken at
        # dump time, well inside the relevant window either way).
        try:
            from harmony_tpu.tracing import flight

            rec = flight.get_recorder()
            rec.event("follower_death", pid=pid,
                      wedged=sorted(int(p) for p in wedged))
            threading.Thread(
                target=lambda: rec.dump(f"follower_death:{pid}", pid=pid),
                daemon=True, name=f"flight-dump-{pid}",
            ).start()
        except Exception:
            pass

    def _proc_executors(self, pid: int) -> List[str]:
        return [
            eid for eid in self.master.executor_ids()
            if self.master.executor(eid).device.process_index == pid
        ]

    def _pod_monitor(self) -> None:
        """Active liveness loop: silence past ``hb_timeout`` confines a
        follower (executors retired, elastic jobs spanning it fenced to
        shrink); FRESH beats from a silence-confined follower
        rehabilitate it (executors restored, shrunk elastic jobs fenced
        to re-grow). Death (reader EOF) is handled by the reader paths
        as before — this thread covers the partial failures only beats
        can reveal."""
        period = max(0.25, min(self.hb_timeout / 4.0, 2.0))
        while not self._monitor_stop.wait(period):
            with self._pod_cond:
                if self._pod_closing:
                    return
                now = time.monotonic()
                stale, fresh = [], []
                for pid in self._followers:
                    if pid in self._dead_followers:
                        continue
                    old = now - self._last_seen.get(pid, now) > self.hb_timeout
                    beat_fresh = (now - self._last_beat.get(pid, 0.0)
                                  <= self.hb_timeout)
                    if old and pid not in self._silenced \
                            and pid not in self._unusable_procs:
                        stale.append(pid)
                    elif beat_fresh and pid in self._silenced:
                        # the BEACON itself resumed (class doc on
                        # _last_beat): the one signal that lifts a
                        # silence confinement
                        fresh.append(pid)
            for pid in stale:
                self._on_follower_silence(pid)
            for pid in fresh:
                self._rehabilitate(pid, reason="heartbeats resumed")

    def _on_follower_silence(self, pid: int) -> None:
        """Infra-dead by SILENCE: the process may be alive (partition,
        muted beacon), so — unlike a death — co-participants are NOT
        presumed wedged (their collectives still have a live peer).
        The pid alone retires; elastic jobs spanning it get a lockstep
        shrink fence so the same submission continues on survivors."""
        with self._pod_cond:
            if pid in self._dead_followers or pid in self._silenced:
                return
            self._silenced.add(pid)
            self._unusable_procs.add(pid)
        retired = self._proc_executors(pid)
        if retired:
            self._scheduler.retire(retired)
        server_log.warning(
            "pod follower %d silent past %.1fs: confined (executors %s "
            "retired); elastic jobs spanning it will shrink",
            pid, self.hb_timeout, retired,
        )
        self._record_pod_event("follower_silenced", pid=pid,
                               retired=retired)
        self._schedule_elastic_fences("shrink", pid)

    def _rehabilitate(self, pid: int, reason: str) -> None:
        """A confined follower proved itself alive again (resumed beats,
        or a replacement JOIN): lift the confinement, restore its
        executors to the scheduler, and offer running shrunk elastic
        jobs a re-grow fence back toward their original layout."""
        with self._pod_cond:
            if pid in self._dead_followers:
                return  # reader saw EOF since; not alive after all
            self._silenced.discard(pid)
            self._unusable_procs.discard(pid)
            if (self._poison_scope == "partial" and not self._dead_followers
                    and not self._unusable_procs):
                # every confined process is back: the pod is whole again
                self._pod_broken = None
                self._poison_scope = None
            self._pod_cond.notify_all()
        restored = self._proc_executors(pid)
        if restored:
            self._scheduler.restore(restored)
        server_log.info("pod follower %d rehabilitated (%s); executors %s "
                        "restored", pid, reason, restored)
        self._record_pod_event("follower_rehabilitated", pid=pid,
                               reason=reason, restored=restored)
        if _elastic.regrow_enabled():
            self._schedule_elastic_fences("regrow", pid)

    def _record_pod_event(self, kind: str, job_id: Optional[str] = None,
                          **fields: Any) -> Dict[str, Any]:
        from harmony_tpu.jobserver import joblog

        ev = joblog.record_event(job_id or "__pod__", kind, **fields)
        with self._pod_cond:
            self.elastic_events.append(dict(ev, job_id=job_id or "__pod__"))
            del self.elastic_events[:-256]
        if self._dashboard is not None:
            # recovery events reach the dashboard summary (kind=recovery
            # rows back its per-job recoveries column); best-effort like
            # every other dashboard post
            self._dashboard.post(job_id or "__pod__", "recovery", dict(ev))
        return ev

    def _elastic_give_up(self, jlog, job_id: str, **fields: Any) -> None:
        """Terminal elastic outcome: one structured event in BOTH the
        per-job log and the pod-level event ring (operators watching the
        status endpoint must see why a degraded tenant stopped
        recovering, not just that it failed)."""
        ev = jlog.event("elastic_give_up", **fields)
        with self._pod_cond:
            self.elastic_events.append(dict(ev, job_id=job_id))
            del self.elastic_events[:-256]
        if self._dashboard is not None:
            self._dashboard.post(job_id, "recovery", dict(ev))

    # -- elastic fences ---------------------------------------------------

    def _schedule_elastic_fences(self, kind: str, pid: int) -> None:
        """Offer every affected RUNNING elastic job a fence: shrink for
        jobs spanning the confined pid, re-grow for shrunk jobs that can
        expand back onto a rehabilitated one."""
        with self._pod_cond:
            targets = []
            for jid, st in self._elastic_active.items():
                if kind == "shrink" and pid in st["procs"]:
                    targets.append(jid)
                elif (kind == "regrow" and st["attempt"] > 0
                      and pid not in st["procs"]
                      and pid in st["original_procs"]):
                    targets.append(jid)
        for jid in targets:
            try:
                self._schedule_elastic_fence(jid, kind)
            except Exception as e:  # noqa: BLE001 - fence is best-effort
                job_logger(jid).warning(
                    "elastic %s fence could not be scheduled: %s: %s",
                    kind, type(e).__name__, e,
                )

    def _schedule_elastic_fence(self, job_id: str, kind: str,
                                origin: str = "failure") -> Optional[int]:
        """Schedule a lockstep elastic fence on a RUNNING attempt: the
        plan broadcast rides the PLAN channel; every participating
        process's chief hook raises the fence at the same epoch (the
        multi-epoch-lead contract of schedule_pod_reshard, same horizon
        arithmetic). Returns the fence epoch, or None when the job is
        too close to its end to be worth reconfiguring. ``origin``
        marks who asked — the failure paths or the policy engine — in
        the structured fence event."""
        from harmony_tpu.dolphin.worker import WorkerTasklet
        from harmony_tpu.jobserver import podplan

        with self._pod_cond:
            st = self._elastic_active.get(job_id)
            if st is None:
                return None
            procs = set(st["procs"])
            att = st["attempt"]
            num_epochs = st["config"].params.num_epochs
        rkey = _elastic.attempt_key(job_id, att)
        with self._lock:
            ent = self._entities.get(job_id)
        cur = 0
        if ent is not None and getattr(ent, "progress", None) is not None:
            cur = ent.progress.starting_epoch()
        else:
            # prefer a HEALTHY participant for the floor query — the
            # silence that triggered a shrink fence may be the very
            # chief we'd otherwise ask; when only confined participants
            # remain, still try (an injected-mute process answers; a
            # real partition doesn't) but with a short timeout so the
            # monitor thread is never stalled the full query window
            with self._pod_cond:
                silenced = set(self._silenced)
            participants = sorted(p for p in procs if p != 0)
            healthy = [p for p in participants if p not in silenced]
            if healthy:
                cur = self._query_remote_epoch(rkey, healthy[0])
            elif participants:
                cur = self._query_remote_epoch(rkey, participants[0],
                                               timeout=5.0)
        epoch = cur + WorkerTasklet.EPOCH_WINDOW + 2
        if epoch >= num_epochs:
            job_logger(job_id).info(
                "elastic %s fence skipped: earliest safe epoch %d is past "
                "the job's end (%d epochs)", kind, epoch, num_epochs,
            )
            return None
        plan = {"epoch": int(epoch), "elastic_fence": kind}
        for p in sorted(p for p in procs if p != 0):
            try:
                self._send_to(p, {"cmd": "PLAN", "job_id": job_id,
                                  "plan": plan})
            except OSError:
                # an unreachable participant misses the fence — but a
                # fence is cooperative teardown, and the job-level waits
                # classify its silence through the normal infra paths
                pass
        podplan.schedule(job_id, plan)
        self._record_pod_event(f"elastic_{kind}_fence", job_id=job_id,
                               epoch=int(epoch), attempt=att,
                               origin=origin)
        return epoch

    # -- policy-engine actuator (jobserver/policy.py) ---------------------

    def _policy_tenants(self) -> Dict[str, Dict[str, Any]]:
        """The running elastic attempts, as the policy engine's
        actuatable-tenant view: live executor grant, attempt index
        (recovery-budget check) and the job's scheduling priority."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._pod_cond:
            for jid, st in self._elastic_active.items():
                cfg = st["config"]
                out[jid] = {
                    "executors": list(st.get("executors") or ()),
                    "attempt": int(st.get("attempt", 0)),
                    "priority": int(getattr(cfg.params, "priority", 0)),
                }
        return out

    def _policy_fence(self, job_id: str, kind: str) -> Optional[int]:
        """Policy actions land through the SAME lockstep fence the
        failure paths use — consistent epoch cut, loss parity, exactly-
        once tiling; the event's origin says the policy asked."""
        return self._schedule_elastic_fence(job_id, kind, origin="policy")

    def _reader_loop(self, pid: int, f) -> None:
        """Owns all reads from follower ``pid``: routes JOB_DONE payloads
        into the report buffer by (job_id, pid), and drives the unit
        arbiter from TU_WAIT/TU_DONE. EOF/read errors mark the follower
        dead and (outside shutdown) poison the pod — a vanished follower
        may be wedged in a collective no later job can satisfy."""
        while True:
            try:
                msg = _recv(f)
            except (OSError, ValueError):
                msg = None
            if msg is None:
                with self._pod_cond:
                    cur = self._followers.get(pid)
                    stale = cur is None or cur[1] is not f
                    if not stale:
                        self._dead_followers.add(pid)
                        self._silenced.discard(pid)  # dead beats silent
                    closing = self._pod_closing
                    self._pod_cond.notify_all()
                if stale:
                    return  # superseded by a reinstated connection
                self.pod_units.proc_done(pid)
                if not closing:
                    self._on_follower_death(pid)
                    self._mark_broken(f"follower {pid} connection lost",
                                      scope="partial")
                return
            # ANY traffic proves the process alive; HEARTBEATs exist so a
            # follower busy inside a long job still produces traffic
            with self._pod_cond:
                self._last_seen[pid] = time.monotonic()
                if msg.get("cmd") == "HEARTBEAT":
                    self._last_beat[pid] = self._last_seen[pid]
                    self._hb_jobs[pid] = set(msg.get("jobs", []))
                    if msg.get("metrics_port"):
                        self._hb_metrics_ports[pid] = int(
                            msg["metrics_port"])
                    self._pod_cond.notify_all()
            if msg.get("cmd") == "HEARTBEAT":
                continue
            if msg.get("cmd") == "TU_WAIT":
                self.pod_units.on_wait(
                    str(msg.get("job_id")), int(msg.get("seq", 0)), pid,
                    retry=bool(msg.get("retry", False)),
                )
                continue
            if msg.get("cmd") == "TU_DONE":
                self.pod_units.on_done(
                    str(msg.get("job_id")), int(msg.get("seq", 0)), pid
                )
                continue
            if msg.get("cmd") in ("EVAL_COLLECTIVE_DONE",
                                  "EVAL_COLLECTIVE_READY"):
                prefix = ("__evalc__"
                          if msg["cmd"] == "EVAL_COLLECTIVE_DONE"
                          else "__evalr__")
                with self._pod_cond:
                    self._reports[
                        (f"{prefix}{msg.get('job_id')}", pid)
                    ] = msg
                    self._pod_cond.notify_all()
                continue
            if msg.get("cmd") == "PROGRESS_REP":
                with self._pod_cond:
                    self._reports[
                        (f"__prog__{msg.get('job_id')}", pid)
                    ] = msg
                    self._pod_cond.notify_all()
                continue
            if msg.get("cmd") == "EVAL_DONE":
                # Shutdown-stage deferred-eval result from a chief follower
                # (the remote analogue of _run_deferred_evals' entries).
                with self._pod_cond:
                    self._remote_eval_results[str(msg.get("job_id"))] = (
                        msg.get("result", {"error": "empty EVAL_DONE"})
                    )
                    self._pod_cond.notify_all()
                continue
            if msg.get("cmd") != "JOB_DONE":
                server_log.warning(
                    "pod: unexpected %r from follower %d", msg.get("cmd"), pid
                )
                continue
            with self._pod_cond:
                self._reports[(str(msg.get("job_id")), pid)] = msg
                while len(self._reports) > 1024:  # bound leader memory
                    self._reports.pop(next(iter(self._reports)))
                self._pod_cond.notify_all()

    def _send_to(self, pid: int, msg: Dict[str, Any]) -> None:
        if self.leader_epoch and "leader_epoch" not in msg:
            # HA fencing: every control-plane message carries the leader
            # epoch; followers reject anything below the highest they
            # have seen, so a deposed leader's late RUN_JOB/PLAN can
            # never act after a takeover (jobserver/ha.py)
            msg = dict(msg, leader_epoch=self.leader_epoch)
        conn, _ = self._followers[pid]
        with self._send_locks[pid]:
            _send(conn, msg)

    def _wait_report(
        self, job_id: str, pid: int, deadline: float
    ) -> Optional[Dict[str, Any]]:
        """Block until follower ``pid`` reports for ``job_id`` (reader
        threads fill the buffer); None on death/timeout. For job-duration
        waits use :meth:`_wait_report_live` — this bounded variant serves
        short protocol acks (eval readiness, progress queries)."""
        key = (job_id, pid)
        with self._pod_cond:
            while key not in self._reports:
                if pid in self._dead_followers:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._pod_cond.wait(timeout=min(remaining, 5.0))
            return self._reports[key]

    def _wait_report_live(
        self, job_id: str, pid: int
    ) -> Optional[Dict[str, Any]]:
        """Block until follower ``pid`` reports for ``job_id``, as long as
        the follower stays LIVE. None only when (a) the connection is
        lost, (b) heartbeats go silent past ``hb_timeout``, or (c) fresh
        heartbeats stop LISTING the job for ``hb_timeout`` without a
        report arriving — a job thread that died without reporting. A
        healthy job may run for hours without tripping anything (the old
        fixed 600s wall declared long remote jobs infra-dead and poisoned
        the pod); a job thread WEDGED in a collective keeps being listed
        and is waited on indefinitely — reference parity (the driver
        waits on tasklet status indefinitely, TaskletRepresenter.java)."""
        key = (job_id, pid)
        missing_since: Optional[float] = None
        with self._pod_cond:
            while key not in self._reports:
                if pid in self._dead_followers:
                    return None
                now = time.monotonic()
                last = self._last_seen.get(pid, 0.0)
                # Short grace RIGHT AFTER staleness onset (total patience
                # = hb_timeout + grace since the last traffic): a
                # silence-confined follower's socket is still up, and its
                # JOB_DONE for a lockstep fence races this wait by design
                # (every process tears down at the same epoch). A pid
                # stale far beyond the window gets no grace — waits on
                # long-mute followers must fail promptly (the auto-resume
                # path resubmits the moment the failure is classified).
                if now - last > self.hb_timeout + min(5.0, self.hb_timeout):
                    return None
                hb = self._hb_jobs.get(pid)
                if hb is not None and job_id not in hb:
                    # generous grace: RUN_JOB delivery and the follower's
                    # registration race the beacon, and a JOB_DONE may be
                    # in flight right behind a beat that dropped the job
                    if missing_since is None:
                        missing_since = now
                    elif now - missing_since > self.hb_timeout:
                        return None
                else:
                    missing_since = None
                self._pod_cond.wait(timeout=2.0)
            return self._reports[key]

    def _collect_reports(
        self, job_id: str, participants: List[int]
    ) -> Dict[int, Dict[str, Any]]:
        """One JOB_DONE per participant; a DEAD-or-silent participant is
        recorded as an infra-error entry rather than wedging the leader
        forever. Liveness-gated, not duration-gated: heartbeats keep the
        wait open for as long as the job actually runs."""
        out: Dict[int, Dict[str, Any]] = {}
        for pid in participants:
            rep = self._wait_report_live(job_id, pid)
            if rep is None:
                # "infra" marks leader-observed transport failures
                # (silence/death) — the follower is gone or wedged — as
                # opposed to a follower-REPORTED job error, after which
                # the follower is alive and serviceable.
                why = ("follower lost" if pid in self._dead_followers
                       else "heartbeat silence")
                out[pid] = {"ok": False, "infra": True, "error": why}
            else:
                out[pid] = rep
        with self._pod_cond:
            for pid in participants:
                self._reports.pop((job_id, pid), None)
        return out

    # -- dispatch override ------------------------------------------------

    def _fail_job(self, config: JobConfig, error: str) -> None:
        jr = self._jobs[config.job_id]
        jr.future.set_exception(RuntimeError(error))
        self._scheduler.on_job_finish(config.job_id)

    def _status(self) -> Dict[str, Any]:
        out = super()._status()
        with self._pod_cond:
            active = {j: sorted(ps)
                      for j, (ps, _) in self._active_procs.items()}
            out["pod"] = {
                "followers": sorted(self._followers),
                "broken": self._pod_broken,
                "active": active,
                "units_granted": self.pod_units.grants_total,
                "units_grant_to_done_s": round(
                    self.pod_units.grant_to_done_s, 4),
                "silenced": sorted(self._silenced),
                "dead": sorted(self._dead_followers),
                "unusable_procs": sorted(self._unusable_procs),
                "reinstated": list(self.reinstated),
                # heartbeat-advertised follower /metrics ports — the
                # history scraper's target discovery, surfaced so an
                # operator can scrape the same endpoints by hand
                "metrics_ports": {str(p): port for p, port
                                  in sorted(self._hb_metrics_ports.items())},
            }
            out["elastic"] = {
                "active": {
                    j: {"attempt": st["attempt"],
                        "procs": sorted(st["procs"])}
                    for j, st in self._elastic_active.items()
                },
                "events": [dict(ev) for ev in self.elastic_events[-32:]],
            }
        return out

    def _scrape_targets(self) -> Dict[str, Any]:
        """The leader's own registry + every live follower whose
        heartbeat advertised an exporter port. Dead/confined followers
        are skipped — their gap is already the signal, and scraping a
        corpse would only slow the loop down to its timeout."""
        targets = super()._scrape_targets()
        with self._pod_cond:
            ports = dict(self._hb_metrics_ports)
            hosts = dict(self._follower_hosts)
            skip = set(self._dead_followers) | set(self._silenced)
        for pid, port in sorted(ports.items()):
            if pid in skip:
                continue
            host = hosts.get(pid) or "127.0.0.1"
            targets[f"pod:{pid}"] = f"http://{host}:{port}/metrics"
        return targets

    @staticmethod
    def _blocks(ps: frozenset, their_ordered: bool, procs: frozenset,
                ordered: bool) -> bool:
        """One conflict predicate for running AND waiting peers: overlap,
        both multi-process, and not both under the unit arbiter."""
        return bool(ps & procs) and len(ps) > 1 and len(procs) > 1 and not (
            ordered and their_ordered
        )

    def _conflicts_locked(self, job_id: str, procs: frozenset,
                          ordered: bool) -> Optional[str]:
        """Admission rule (module doc): a running job blocks ``procs`` iff
        the sets overlap, BOTH span more than one process, and the pair is
        not covered by the cross-job unit protocol (both pod_ordered).

        Why single-process jobs never conflict: a deadlock needs two
        multi-device programs enqueued in OPPOSITE orders on two shared
        devices, and a single-process job's shared-device pairs all live
        in one process, whose dispatch lock enqueues each program
        atomically across its devices — every shared pair sees the same
        order. FIFO fairness: a job WAITING on admission reserves its
        processes against every LATER arrival it would conflict with —
        including brand-new candidates that hold no ticket yet (they rank
        newest) — so a stream of small jobs cannot starve a pod-spanning
        one."""
        for jid, (ps, their_ordered) in self._active_procs.items():
            if self._blocks(ps, their_ordered, procs, ordered):
                return jid
        mine = self._admission_waiting.get(job_id)
        my_ticket = mine[0] if mine is not None else float("inf")
        for jid, (ticket, ps, their_ordered) in self._admission_waiting.items():
            if (jid != job_id and ticket < my_ticket
                    and self._blocks(ps, their_ordered, procs, ordered)):
                return jid  # older waiter holds these processes
        return None

    def _dispatch(self, config: JobConfig, executor_ids: List[str]) -> None:
        if self._elastic_eligible(config):
            # ONE span for the whole elastic submission: every attempt's
            # pod.dispatch span nests under it, so a trace shows the
            # fences and recovery attempts as one connected story
            with trace_span(
                "elastic.submission",
                parent=self._trace_parent_of(config),
                job_id=config.job_id,
            ):
                self._dispatch_elastic(config, executor_ids)
            return
        self._dispatch_once(config, executor_ids)
        self._maybe_auto_resume(config, executor_ids)

    def _elastic_eligible(self, config: JobConfig) -> bool:
        """user.elastic_shrink jobs that can actually be recovered in
        place: a dolphin job with a chain (the recovery point) and a
        PRIVATE model table (a shared table's state belongs to every
        tenant — rebuilding it under one would corrupt the others)."""
        if not config.user.get("elastic_shrink"):
            return False
        ok = (config.app_type == "dolphin" and not config.tables
              and config.params.model_chkp_period > 0
              and self._chkp_root is not None)
        if not ok:
            job_logger(config.job_id).warning(
                "elastic_shrink ignored: needs app_type=dolphin, a private "
                "model table, model_chkp_period > 0 and a server chkp_root"
            )
        return ok

    def _dispatch_elastic(self, config: JobConfig,
                          executor_ids: List[str]) -> None:
        """The elastic dispatch loop — ONE submission, many attempts.

        Each attempt runs through the ordinary pod dispatch under an
        attempt-keyed identity (jobserver/elastic.attempt_key) against a
        PRIVATE inner future; the client-visible outer future resolves
        only when an attempt completes or recovery is exhausted — no
        resubmit, no duplicate-id dance, job status shows one running
        job throughout. Failure classification per attempt:

          * elastic FENCE (shrink or re-grow) — planned lockstep
            teardown; recover and continue;
          * infra-shaped failure (participant death/silence, an
            infra_suspect give-up) — recover on survivors;
          * anything else — the job failed on its own terms: fail the
            submission (never resubmitted to fail identically).
        """
        outer = self._jobs[config.job_id]
        jlog = job_logger(config.job_id)
        cfg, execs = config, list(executor_ids)
        original_procs = frozenset(
            self.master.executor(e).device.process_index
            for e in executor_ids
        )
        recoveries = 0
        events: List[Dict[str, Any]] = []
        last_exc: Optional[BaseException] = None
        try:
            while True:
                att = _elastic.attempt_of(cfg)
                inner = JobResult()
                with self._lock:
                    self._jobs[config.job_id] = inner
                with self._pod_cond:
                    self._elastic_active[config.job_id] = {
                        "attempt": att,
                        "procs": frozenset(
                            self.master.executor(e).device.process_index
                            for e in execs
                        ),
                        "original_procs": original_procs,
                        "config": cfg,
                        # the live grant — what the policy engine's
                        # grow/shrink/pack targets are computed FROM
                        "executors": list(execs),
                    }
                try:
                    self._dispatch_once(cfg, execs)
                finally:
                    with self._pod_cond:
                        self._elastic_active.pop(config.job_id, None)
                exc = inner.future.exception()
                if exc is None:
                    result = dict(inner.future.result())
                    if att or events:
                        result["elastic"] = {
                            "attempts": att + 1,
                            "recoveries": recoveries,
                            "events": list(events),
                        }
                    outer.future.set_result(result)
                    return
                last_exc = exc
                fence = getattr(exc, "elastic_fence", None)
                with self._pod_cond:
                    infra = config.job_id in self._infra_failed
                    self._infra_failed.discard(config.job_id)
                infra = infra or bool(getattr(exc, "infra_suspect", False))
                if fence is None and not infra:
                    self._elastic_give_up(
                        jlog, config.job_id,
                        reason="job failed on its own terms",
                        error=f"{type(exc).__name__}: {exc}"[:300])
                    return
                if recoveries >= _elastic.max_shrinks():
                    self._elastic_give_up(
                        jlog, config.job_id,
                        reason=f"recovery cap {_elastic.max_shrinks()} "
                               "reached (HARMONY_ELASTIC_MAX_SHRINKS)",
                    )
                    return
                kind = "regrow" if fence == "regrow" else "shrink"
                try:
                    with trace_span(
                        "elastic.plan_recovery", job_id=config.job_id,
                        kind=kind,
                        attempt=_elastic.attempt_key(config.job_id, att + 1),
                    ):
                        plan = self._plan_elastic_recovery(
                            config, execs, att, kind, executor_ids, events
                        )
                except BaseException as e:  # noqa: BLE001 - give up cleanly
                    self._elastic_give_up(
                        jlog, config.job_id,
                        reason=f"recovery planning failed: "
                               f"{type(e).__name__}: {e}"[:300],
                    )
                    return
                if plan is None:
                    return
                cfg, execs = plan
                recoveries += 1
        finally:
            with self._lock:
                self._jobs[config.job_id] = outer
            if not outer.future.done():
                outer.future.set_exception(
                    last_exc if last_exc is not None else RuntimeError(
                        "elastic dispatch ended without a result")
                )
            # the submission is over either way: release this process's
            # retained recovery blocks (private tables are namespaced by
            # job id; follower processes rely on the cache's LRU cap)
            from harmony_tpu.checkpoint import manager as _chkp_mgr

            _chkp_mgr.drop_recovery_cache(prefix=f"{config.job_id}:")
            # and drop any unconsumed policy-planned grant: a stale pin
            # (possibly SHARED) must never leak to a future submission
            # reusing this job id
            try:
                self._scheduler.plan_grant(config.job_id, None)
            except Exception:
                pass

    def _plan_elastic_recovery(
        self,
        config: JobConfig,
        prev_execs: List[str],
        prev_attempt: int,
        kind: str,
        original_execs: List[str],
        events: List[Dict[str, Any]],
    ) -> "Optional[Tuple[JobConfig, List[str]]]":
        """Compute the next attempt: rehabilitate survivors confined only
        transitively, re-acquire executors (survivors preferred for
        shrink, the original layout for re-grow), verify a committed
        chain exists, and mint the recovery config. None = no viable
        recovery (an event records why; the submission then fails with
        the attempt's error)."""
        from harmony_tpu import faults
        from harmony_tpu.checkpoint.manager import CheckpointManager

        jlog = job_logger(config.job_id)
        if faults.armed():
            faults.site("pod.shrink_plan" if kind == "shrink"
                        else "pod.regrow",
                        job=config.job_id, attempt=prev_attempt)
        # Rehabilitation: a process confined only TRANSITIVELY (it shared
        # this job with the dead/silent one) that nonetheless REPORTED —
        # proof its threads left the collectives — and still heartbeats
        # is a survivor, not a casualty.
        reports = self.pod_reports.get(config.job_id, {})
        now = time.monotonic()
        rehab: List[int] = []
        with self._pod_cond:
            for pid, rep in reports.items():
                if (pid in self._unusable_procs
                        and pid not in self._dead_followers
                        and pid not in self._silenced
                        and not rep.get("infra")
                        and now - self._last_seen.get(pid, 0.0)
                        <= self.hb_timeout):
                    self._unusable_procs.discard(pid)
                    rehab.append(pid)
        for pid in rehab:
            restored = self._proc_executors(pid)
            if restored:
                self._scheduler.restore(restored)
            self._record_pod_event("follower_rehabilitated",
                                   job_id=config.job_id, pid=pid,
                                   reason="reported for the failed attempt")
        with self._pod_cond:
            unusable = set(self._unusable_procs)

        def proc(e: str) -> int:
            return self.master.executor(e).device.process_index

        base = original_execs if kind == "regrow" else prev_execs
        preferred = [e for e in base if proc(e) not in unusable]
        granted = [
            e for e in self._scheduler.reacquire(config.job_id, preferred)
            if proc(e) not in unusable
        ]
        if not granted:
            self._elastic_give_up(jlog, config.job_id,
                                  reason="no usable executors to recover on")
            return None
        mgr = CheckpointManager.for_job(self._chkp_root, config.job_id)
        chain_prefix = f"{config.job_id}:"
        if not any(c.startswith(chain_prefix)
                   for c in mgr.list_checkpoints()):
            self._elastic_give_up(jlog, config.job_id,
                                  reason="no committed chain checkpoints yet")
            return None
        lost = [e for e in prev_execs if e not in granted]
        new_cfg = ConfigBase.from_dict(config.to_dict())
        new_cfg.user["elastic_recovery"] = {
            "attempt": prev_attempt + 1,
            "kind": kind,
            "lost_executors": lost,
        }
        ev = jlog.event(
            f"elastic_{kind}",
            attempt=prev_attempt + 1,
            executors=list(granted),
            lost_executors=lost,
            procs=sorted({proc(e) for e in granted}),
        )
        events.append(dict(ev))
        with self._pod_cond:
            self.elastic_events.append(dict(ev, job_id=config.job_id))
            del self.elastic_events[:-256]
        if self._dashboard is not None:
            self._dashboard.post(config.job_id, "recovery", dict(ev))
        return new_cfg, granted

    def _dispatch_once(self, config: JobConfig,
                       executor_ids: List[str]) -> None:
        att = _elastic.attempt_of(config)
        with trace_span(
            "pod.dispatch",
            parent=self._trace_parent_of(config),
            job_id=config.job_id,
            # the job@aN attempt key rides as a span annotation, so a
            # trace query tells recovery attempts apart at a glance
            attempt=_elastic.attempt_key(config.job_id, att),
        ):
            self._dispatch_once_inner(config, executor_ids)

    def _dispatch_once_inner(self, config: JobConfig,
                             executor_ids: List[str]) -> None:
        jlog = job_logger(config.job_id)
        procs = frozenset(
            self.master.executor(e).device.process_index for e in executor_ids
        )
        if config.optimizer and len(procs) > 1 and 0 not in procs:
            # Reject HERE, before any RUN_JOB is sent: the optimizer loop
            # needs the pod plan channel, which only exists where process 0
            # participates. (The entity guard is symmetric too — this is
            # the clean-failure layer that keeps followers out of it
            # entirely.)
            self._fail_job(
                config,
                f"optimizer={config.optimizer!r} on a multi-process grant "
                "needs the grant to include the pod leader (process 0), "
                "which owns the plan channel",
            )
            return
        # Multi-worker multi-process jobs are legal: the entity wires a
        # DispatchTurnstile so every process's worker threads enqueue
        # their global programs in the same deterministic order
        # (dolphin/master.py), and the per-process SSP controllers see
        # identical sync orders — identical decisions, no broadcast.
        # Multi-process DOLPHIN jobs additionally run the cross-job unit
        # protocol (runtime/podunits.py), so they may OVERLAP each other —
        # the reference's share-all default. user.pod_isolated opts a job
        # OUT (exclusive execution, serialized at admission — no unit
        # round-trips, no co-tenant interleaving). Admission: wait until
        # no running job conflicts (see _conflicts_locked); while waiting,
        # the job's FIFO ticket reserves its processes against later
        # arrivals.
        pod_ordered = (config.app_type in ("dolphin", "pregel")
                       and len(procs) > 1
                       and not bool(config.user.get("pod_isolated")))
        admitted = False
        with self._pod_cond:
            while True:
                # TOTAL poison fails everything; PARTIAL (a confined
                # follower death) fails only jobs touching the unusable
                # processes — survivors and auto-resumes keep running.
                # A broken flag with UNKNOWN scope (set outside
                # _mark_broken) is conservatively total.
                if self._pod_broken and self._poison_scope != "partial":
                    break
                if procs & self._unusable_procs:
                    break
                if self._conflicts_locked(
                        config.job_id, procs, pod_ordered) is None:
                    self._active_procs[config.job_id] = (procs, pod_ordered)
                    self._admission_waiting.pop(config.job_id, None)
                    admitted = True
                    self._pod_cond.notify_all()  # ticket holders re-check
                    break
                if config.job_id not in self._admission_waiting:
                    self._admission_ticket += 1
                    self._admission_waiting[config.job_id] = (
                        self._admission_ticket, procs, pod_ordered
                    )
                self._pod_cond.wait(timeout=1.0)
            if not admitted:
                self._admission_waiting.pop(config.job_id, None)
        if not admitted:
            self._fail_job(
                config,
                f"pod is broken ({self._pod_broken}); the job's processes "
                f"{sorted(procs & self._unusable_procs) or ''} are "
                "unusable — followers may be wedged in collectives",
            )
            return
        t0 = time.monotonic()
        # Attempt key: identical to job_id for ordinary jobs; elastic
        # recovery attempts get a suffixed identity so reports, unit
        # messages and heartbeat listings from a superseded attempt can
        # never be misattributed to the live one (jobserver/elastic.py).
        att = _elastic.attempt_of(config)
        rkey = _elastic.attempt_key(config.job_id, att)
        if pod_ordered:
            # the arbiter must know the job BEFORE any participant's first
            # TU_WAIT can arrive (i.e. before RUN_JOB is sent); recovery
            # attempts inherit their predecessor's fair-share deficit
            self.pod_units.register_job(
                rkey, procs,
                inherit_from=(_elastic.attempt_key(config.job_id, att - 1)
                              if att > 0 else None),
            )
        try:
            participants = sorted(p for p in procs if p != 0)
            run_local = 0 in procs
            with self._pod_cond:
                self._job_info[config.job_id] = (
                    participants, config.num_workers or len(executor_ids)
                )
                while len(self._job_info) > 1024:
                    self._job_info.pop(next(iter(self._job_info)))
            if participants:
                jlog.info(
                    "pod: RUN_JOB to follower(s) %s (chief=%d, local=%s)",
                    participants, min(procs), run_local,
                )
                msg = {
                    "cmd": "RUN_JOB",
                    "conf": config.to_dict(),
                    "executor_ids": list(executor_ids),
                    "chief_pid": min(procs),
                    # the dispatch span's wire context: follower-side job
                    # spans re-parent onto it, so one trace_id spans the
                    # leader->follower hop (tracing/span.py's TraceInfo
                    # analogue, finally used ACROSS processes)
                    "trace": wire_context(),
                    # elastic attempt index (0 for ordinary jobs): keys
                    # the follower's entity registry, unit client and
                    # JOB_DONE routing per attempt
                    "att": att,
                    # Participate in the cross-job unit protocol (share-all
                    # overlap safety — runtime/podunits.py).
                    "pod_ordered": pod_ordered,
                    # Followers stage model checkpoints under the same root
                    # the leader would use, so carved jobs keep the
                    # checkpoint-chain + deferred-eval features.
                    "chkp_root": self._chkp_root,
                    # Participants must build the entity with the SAME aux
                    # components: the TaskUnit schedulers change how the
                    # worker phases its device dispatches (fused vs split
                    # PULL/COMP/PUSH), and any asymmetry there is a
                    # cross-process collective mismatch.
                    "cpu_slots": self.local_taskunit.cpu_slots,
                    "net_slots": self.local_taskunit.net_slots,
                }
                try:
                    for pid in participants:
                        self._send_to(pid, msg)
                except OSError as e:
                    # A partially-delivered RUN_JOB cannot train (the SPMD
                    # collectives need every participant) — fail the job
                    # and POISON the pod: followers that did get the
                    # message are now blocked in collectives.
                    self._mark_broken(f"RUN_JOB send failed: {e}")
                    self._fail_job(config, f"pod RUN_JOB send failed: {e}")
                    return
            if run_local:
                super()._dispatch(config, executor_ids)
            else:
                # The leader holds none of this job's devices: the chief
                # participant's report is the job result.
                self._resolve_remote(config, participants, rkey)
            if participants:
                reports = self._collect_reports(rkey, participants)
                # Give-up escalation: a follower that FAILED the job on an
                # exhausted-retry infra error (transport/storage — its
                # report carries infra_suspect, the follower itself is
                # alive and serviceable) feeds the same auto-resume
                # evidence a death would, WITHOUT retiring any process.
                if any(not r.get("ok") and r.get("infra_suspect")
                       for r in reports.values()):
                    with self._pod_cond:
                        self._record_infra_failed_locked(config.job_id)
                # A participant that never reported is wedged (likely stuck
                # in a collective): any later job overlapping its process
                # could never complete — poison the pod.
                dead = [pid for pid, r in reports.items() if r.get("infra")]
                if dead:
                    # death-driven: confine the damage (idempotent with
                    # the reader-EOF path) and poison PARTIALLY so
                    # unaffected jobs and auto-resumes keep running.
                    # For ELASTIC jobs only, SILENCED pids are excluded
                    # from the wedge marking: the monitor already
                    # confined them, their socket is intact, and wedging
                    # co-participants would retire the very survivors
                    # the shrink recovers on (the capped recovery loop
                    # fails loudly if they turn out wedged after all).
                    # Non-elastic jobs keep the conservative stance — a
                    # silence that is really a FIN-less host death leaves
                    # peers stuck in its collectives.
                    elastic = bool(config.user.get("elastic_shrink"))
                    with self._pod_cond:
                        self._record_infra_failed_locked(config.job_id)
                        hard_dead = [p for p in dead
                                     if not elastic
                                     or p not in self._silenced]
                    for pid in hard_dead:
                        self._on_follower_death(pid)
                    if hard_dead:
                        self._mark_broken(
                            f"follower(s) {hard_dead} never reported for "
                            f"{config.job_id}", scope="partial",
                        )
                with self._pod_cond:  # concurrent dispatch threads trim too
                    self.pod_reports[config.job_id] = reports
                    while len(self.pod_reports) > 256:  # bound leader memory
                        self.pod_reports.pop(next(iter(self.pod_reports)))
                    for pid, rep in reports.items():
                        if rep.get("has_deferred_eval"):
                            self._remote_evals[config.job_id] = pid
        finally:
            from harmony_tpu.jobserver import podplan

            podplan.clear(config.job_id)  # unapplied plans die with the job
            if pod_ordered:
                # after report collection: every participant's TU_DONEs
                # precede its JOB_DONE on the same socket, so nothing of
                # this job is still in flight at the arbiter
                self.pod_units.deregister_job(rkey)
            with self._pod_cond:
                # deregister so schedule_pod_reshard on a finished job
                # raises KeyError instead of accreting stale plans
                self._job_info.pop(config.job_id, None)
                self.job_walls[config.job_id] = (t0, time.monotonic())
                while len(self.job_walls) > 1024:
                    self.job_walls.pop(next(iter(self.job_walls)))
                self._active_procs.pop(config.job_id, None)
                self._pod_cond.notify_all()

    def _maybe_auto_resume(self, config: JobConfig,
                           executor_ids: List[str]) -> None:
        """Auto-resume (beyond the reference's fail-fast stubs,
        JobServerDriver.java:271-298): a ``user.auto_resume`` job with a
        checkpoint chain that just FAILED because its processes became
        unusable (a follower died) is resubmitted with
        ``resume_from_chain`` — the scheduler, whose dead executors were
        retired, grants surviving ones, and the entity restores the last
        committed chain checkpoint and continues from its epoch."""
        jr = self._jobs.get(config.job_id)
        if jr is None or not jr.future.done() or jr.future.exception() is None:
            return
        if not (config.user.get("auto_resume")
                and config.params.model_chkp_period > 0
                and self._chkp_root
                and not config.user.get("resume_from_chain")):
            return
        with self._pod_cond:
            # evidence that THIS job's failure was infra-observed (a
            # participant died/went silent while it ran, or a participant
            # reported an infra_suspect give-up) — a job failing on its
            # own terms after some unrelated earlier death must NOT be
            # resubmitted to fail identically again
            infra = config.job_id in self._infra_failed
            self._infra_failed.discard(config.job_id)
        if not infra:
            # leader-LOCAL evidence: the future's exception carries the
            # infra_suspect marker (a bounded-retry give-up in this
            # process — faults.retry.InfraTransientError)
            infra = bool(getattr(jr.future.exception(), "infra_suspect",
                                 False))
        if not infra:
            return  # the job failed on its own terms, not infra death
        from harmony_tpu.checkpoint.manager import CheckpointManager

        mgr = CheckpointManager.for_job(self._chkp_root, config.job_id)
        prefix = f"{config.job_id}:"
        if not any(c.startswith(prefix) for c in mgr.list_checkpoints()):
            server_log.warning(
                "auto-resume of %s skipped: no chain checkpoints yet",
                config.job_id,
            )
            return
        new_cfg = ConfigBase.from_dict(config.to_dict())
        new_cfg.user["resume_from_chain"] = True
        server_log.info(
            "auto-resuming %s from its checkpoint chain on surviving "
            "executors", config.job_id,
        )
        self.auto_resumed.append(config.job_id)
        try:
            self.submit(new_cfg)
        except Exception as e:  # noqa: BLE001 - the original failure stands
            server_log.error("auto-resume submit for %s failed: %s",
                             config.job_id, e)

    def _query_remote_epoch(self, job_id: str, chief: int,
                            timeout: float = 30.0) -> int:
        """Ask the chief follower for its observed epoch floor (jobs the
        leader does not participate in have no local entity to read). A
        silent or unreachable chief FAILS the query — a guessed floor of 0
        is exactly the divergence hazard the horizon check exists to
        prevent."""
        key = f"__prog__{job_id}"
        try:
            self._send_to(chief, {"cmd": "PROGRESS_REQ", "job_id": job_id})
        except OSError as e:
            raise RuntimeError(
                f"progress query to follower {chief} failed: {e}"
            ) from None
        rep = self._wait_report(key, chief, time.monotonic() + timeout)
        with self._pod_cond:
            self._reports.pop((key, chief), None)
        if rep is None:
            raise RuntimeError(
                f"follower {chief} did not answer the progress query for "
                f"{job_id}; rejecting the plan (no observed epoch floor)"
            )
        return int(rep.get("epoch", 0))

    def schedule_pod_reshard(
        self, job_id: str, src: str, dst: str, num_blocks: int, epoch: int
    ) -> None:
        """Plan-driven migration on a RUNNING pod job (ref: the driver's
        MoveInitMsg flow): broadcast the move to every participant; each
        process — leader included — applies it at its chief worker's
        epoch-``epoch`` hook, the deterministic lockstep point (see
        jobserver/podplan.py, including the multi-epoch-lead contract).
        Multi-worker jobs apply inside the chief's turnstile turn, so any
        worker count is legal (ref: PlanExecutorImpl.java:41-130 — plans
        apply regardless of worker count)."""
        from harmony_tpu.dolphin.worker import WorkerTasklet
        from harmony_tpu.jobserver import podplan

        with self._pod_cond:
            info = self._job_info.get(job_id)
        if info is None:
            raise KeyError(f"unknown (or finished) pod job {job_id}")
        participants, workers = info
        # Enforce the multi-epoch-lead contract against an OBSERVED epoch
        # floor: the window decision COVERING the plan epoch must happen
        # after every process holds the plan, so the epoch needs at least
        # a full window horizon of lead. The floor comes from the leader's
        # own entity when it participates (its tracker is fed per epoch
        # for every worker count), else from the chief follower — queried,
        # never guessed.
        with self._lock:
            ent = self._entities.get(job_id)
        cur = 0
        if ent is not None and getattr(ent, "progress", None) is not None:
            cur = ent.progress.starting_epoch()
        elif participants:
            cur = self._query_remote_epoch(job_id, min(participants))
        horizon = WorkerTasklet.EPOCH_WINDOW + 1
        if epoch < cur + horizon:
            raise ValueError(
                f"plan epoch {epoch} is inside the window horizon (job at "
                f"~epoch {cur}; need >= {cur + horizon}): a plan landing "
                "mid-window would apply at divergent points across "
                "processes"
            )
        plan = {"epoch": int(epoch), "src": src, "dst": dst,
                "num_blocks": int(num_blocks)}
        try:
            for pid in participants:
                self._send_to(pid, {"cmd": "PLAN", "job_id": job_id,
                                    "plan": plan})
        except OSError as e:
            # a PARTIALLY delivered plan is the divergence hazard itself:
            # some processes would apply the move, others never — poison
            # like the RUN_JOB path so nothing later wedges silently
            self._mark_broken(f"PLAN broadcast failed: {e}")
            raise
        podplan.schedule(job_id, plan)

    def _entity_extras(self, config: JobConfig,
                       executor_ids: List[str]) -> Dict[str, Any]:
        """Wire the pod channels into multi-process single-thread
        entities: the optimizer loop hands plans to schedule_pod_reshard
        instead of executing reshard collectives from its own thread, and
        the shutdown-stage deferred model eval runs as a pod collective
        through the eval channel."""
        procs = {
            self.master.executor(e).device.process_index
            for e in executor_ids
        }
        if len(procs) > 1:
            extras: Dict[str, Any] = {
                "pod_plan_sink": self.schedule_pod_reshard,
            }
            if (config.app_type in ("dolphin", "pregel")
                    and not bool(config.user.get("pod_isolated"))):
                # Leader-local leg of the cross-job unit protocol: the
                # entity wraps every global-dispatch region in a unit so
                # overlapping tenants enqueue in the arbiter's one order.
                client = leader_client(self.pod_units,
                                       _elastic.config_attempt_key(config))
                extras["pod_unit_scope"] = client.scope
                extras["pod_unit_contended"] = client.contended
            # The collective deferred eval runs at SHUTDOWN on one thread
            # per process — worker-count independent (the chain it
            # replays is now written for any worker count too: the
            # snapshot hook rides the chief's turnstile turn).
            extras["pod_eval_channel"] = self._pod_eval_channel
            if (config.params.offline_model_eval
                    and config.params.model_chkp_period > 0):
                # registered ONLY for jobs that will actually run the
                # collective eval at shutdown — unconditional
                # registration would let unrelated jobs FIFO-evict a
                # live entry and turn its broadcast into a silent
                # no-op (the leader would then evaluate alone and
                # wedge in its collectives)
                participants = sorted(p for p in procs if p != 0)
                with self._pod_cond:
                    self._eval_participants[config.job_id] = participants
                    while len(self._eval_participants) > 1024:
                        self._eval_participants.pop(
                            next(iter(self._eval_participants)))
            return extras
        return {}

    def _broadcast_eval_decision(self, participants: List[int],
                                 job_id: str, go: bool) -> None:
        cmd = "EVAL_GO" if go else "EVAL_ABORT"
        for pid in participants:
            try:
                self._send_to(pid, {"cmd": cmd, "job_id": job_id})
            except OSError as e:
                if not go:
                    continue  # an unreachable follower cannot be aborted
                    # harder; it is already out of the protocol
                # a PARTIAL GO is unrecoverable: recipients enter
                # collectives the rest never join — poison, and the
                # caller must NOT enter its own collectives
                self._mark_broken(f"EVAL_GO send failed: {e}")
                raise RuntimeError(
                    f"EVAL_GO broadcast failed: {e}"
                ) from None

    def _pod_eval_channel(self, phase: str, job_id: str,
                          payload: Optional[Dict[str, Any]] = None,
                          timeout: float = 180.0) -> None:
        """Two-phase channel for the collective deferred eval:
        phase "start" broadcasts EVAL_COLLECTIVE so followers enter the
        restore+evaluate collectives in lockstep with the leader's eval;
        phase "finish" waits (bounded) for their EVAL_COLLECTIVE_DONE
        acks — a silent follower is recorded, never waited on forever."""
        with self._pod_cond:
            participants = self._eval_participants.get(job_id, [])
        if not participants:
            return
        if phase == "start":
            # Three-phase handshake: broadcast -> collect READINESS acks
            # (followers stage everything fallible HOST-SIDE first) ->
            # GO only when every participant is ready, else ABORT. A
            # follower failing BEFORE the collectives therefore aborts
            # the whole eval cleanly — nobody enters collectives that
            # cannot complete. Only a failure AFTER GO (mid-collective,
            # the finish phase's domain) poisons the pod.
            try:
                for pid in participants:
                    self._send_to(pid, {"cmd": "EVAL_COLLECTIVE",
                                        "job_id": job_id, **(payload or {})})
            except OSError as e:
                # partial broadcast: recipients sit in the READY wait (a
                # bounded socket read, not a collective) — abort them
                self._broadcast_eval_decision(participants, job_id, go=False)
                raise RuntimeError(
                    f"EVAL_COLLECTIVE broadcast failed: {e}"
                ) from None
            deadline = time.monotonic() + timeout
            failures = []
            for pid in participants:
                rep = self._wait_report(f"__evalr__{job_id}", pid, deadline)
                if rep is None or not rep.get("ok"):
                    failures.append(
                        (pid, "no readiness ack" if rep is None
                         else rep.get("error")))
            with self._pod_cond:
                for pid in participants:
                    self._reports.pop((f"__evalr__{job_id}", pid), None)
            if failures:
                self._broadcast_eval_decision(participants, job_id, go=False)
                with self._pod_cond:
                    self._eval_participants.pop(job_id, None)
                raise RuntimeError(
                    f"collective eval aborted (followers not ready): "
                    f"{failures}"
                )
            self._broadcast_eval_decision(participants, job_id, go=True)
            return
        deadline = time.monotonic() + timeout
        for pid in participants:
            rep = self._wait_report(f"__evalc__{job_id}", pid, deadline)
            if rep is None or not rep.get("ok"):
                # silence = wedged in a collective; ok=False = it bailed
                # BEFORE the collectives while the others entered them.
                # Either way the eval collectives cannot all complete:
                # record the one diagnosable fact and poison.
                why = ("never acked" if rep is None
                       else f"failed: {rep.get('error')}")
                self._mark_broken(
                    f"collective eval for {job_id}: follower {pid} {why}"
                )
        with self._pod_cond:
            for pid in participants:
                self._reports.pop((f"__evalc__{job_id}", pid), None)
            self._eval_participants.pop(job_id, None)

    def _resolve_remote(self, config: JobConfig, participants: List[int],
                        rkey: Optional[str] = None) -> None:
        """Leader-side completion for a job running wholly on followers:
        the lowest participating pid is the job chief; its JOB_DONE carries
        the sanitized result that resolves the leader's future (mirroring
        what the base _dispatch does for local jobs, including the
        scheduler.on_job_finish in finally)."""
        jr = self._jobs[config.job_id]
        jlog = job_logger(config.job_id)
        chief = min(participants)
        key = rkey or config.job_id
        t0 = time.monotonic()
        try:
            rep = self._wait_report_live(key, chief)
            if rep is None:
                with self._pod_cond:  # infra-observed: resume-eligible
                    self._record_infra_failed_locked(config.job_id)
                raise RuntimeError(
                    f"chief follower {chief} never reported for "
                    f"{config.job_id} (connection lost or heartbeat "
                    "silence)"
                )
            if not rep.get("ok"):
                if rep.get("infra_suspect"):
                    # chief-reported give-up on an infra fault: resume-
                    # eligible (the _dispatch leg records participants'
                    # flags; this covers the chief-only result path)
                    with self._pod_cond:
                        self._record_infra_failed_locked(config.job_id)
                err = RuntimeError(
                    f"remote job failed on follower {chief}: "
                    f"{rep.get('error', 'unknown error')}"
                )
                if rep.get("elastic_fence"):
                    # the chief hit a planned elastic fence, not a bug:
                    # carry the marker so the elastic loop classifies it
                    err.elastic_fence = str(rep["elastic_fence"])
                raise err
            result = rep.get("result") or {
                "job_id": config.job_id, "workers": rep.get("workers", {})
            }
            jlog.info("finished remotely in %.1fs (chief=%d)",
                      time.monotonic() - t0, chief)
            jr.future.set_result(result)
        except BaseException as e:  # noqa: BLE001 - delivered via future
            jlog.error("remote job failed: %s: %s", type(e).__name__, e)
            jr.future.set_exception(e)
        finally:
            self._scheduler.on_job_finish(config.job_id)

    def _on_closing(self, timeout: Optional[float] = 300.0) -> None:
        """Pod teardown, run by the base shutdown BEFORE the CLOSED
        transition (observers keyed on CLOSED — e.g. the pod worker's exit
        loop — must see the remote eval results already collected).

        The job futures resolve BEFORE participant reports are collected,
        so a client reacting to job completion can reach shutdown while
        _dispatch threads are still reading JOB_DONEs; wait out the
        active set so socket teardown follows those collections."""
        deadline = time.monotonic() + 30.0
        self._monitor_stop.set()
        with self._pod_cond:
            self._pod_cond.wait_for(
                lambda: not self._active_procs,
                timeout=max(0.0, deadline - time.monotonic()),
            )
            self._pod_closing = True
        if self._followers:
            for pid in sorted(self._followers):
                try:
                    self._send_to(pid, {"cmd": "SHUTDOWN"})
                except OSError:
                    pass
            # Chief followers run their jobs' deferred model evals on
            # SHUTDOWN (the remote leg of _run_deferred_evals); collect
            # their EVAL_DONEs before tearing the sockets down.
            with self._pod_cond:
                pending = dict(self._remote_evals)
            if pending:
                deadline = time.monotonic() + (timeout or 300.0)
                with self._pod_cond:
                    self._pod_cond.wait_for(
                        lambda: all(
                            j in self._remote_eval_results
                            or pid in self._dead_followers
                            for j, pid in pending.items()
                        ),
                        timeout=max(0.0, deadline - time.monotonic()),
                    )
                    for j, pid in pending.items():
                        self.eval_results[j] = self._remote_eval_results.get(
                            j, {"error": f"follower {pid} never sent EVAL_DONE"}
                        )
            for conn, f in self._followers.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._followers.clear()
        if self._pod_sock is not None:
            self._pod_sock.close()
            self._pod_sock = None


class PodFollower:
    """Evaluator-side loop on processes 1..N-1 of a pod.

    Mirrors the leader's job lifecycle against a local ETMaster whose
    executor ids — produced by the same fresh-process allocation order —
    name the same global devices as the leader's. RUN_JOBs run on their own
    threads: a follower may participate in several concurrent jobs (each
    confined to this process, or process-disjoint multi-process jobs the
    leader's admission rule lets through), sharing one process-wide
    GlobalTaskUnitScheduler exactly like the leader's local jobs do."""

    def __init__(self, leader_host: str, pod_port: int, pid: int,
                 num_executors: int, join_timeout: float = 300.0,
                 reconnect: Optional[bool] = None,
                 leader_addrs: Optional[List[Tuple[str, int]]] = None
                 ) -> None:
        self.pid = pid
        self._join_timeout = join_timeout
        # Control-plane HA (jobserver/ha.py): when enabled, a lost
        # leader connection means LEADER CHANGE, not pod death — the
        # follower re-HELLOs the (possibly new) leader, keeping its
        # executors, entities and running job threads alive through the
        # takeover window.
        if reconnect is None:
            from harmony_tpu.jobserver import ha as _ha

            reconnect = _ha.ha_enabled()
        self._reconnect = bool(reconnect)
        self._leader_addrs = list(leader_addrs or [(leader_host, pod_port)])
        #: highest leader epoch observed; lower-epoch messages are a
        #: deposed leader's late writes and are rejected (fencing)
        self._leader_epoch = 0
        self.stale_rejected = 0
        # The leader may still be initializing its runtime when followers
        # come up (hosts boot in any order): retry until the deadline.
        deadline = time.monotonic() + join_timeout
        from harmony_tpu.faults.partition import fault_connect

        while True:
            try:
                self._sock = fault_connect(
                    (leader_host, pod_port), role="pod.join", timeout=10.0
                )
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self._sock.settimeout(None)  # RUN_JOB may arrive much later
        self._file = self._sock.makefile("r")
        self._send_lock = threading.Lock()
        self._pod_units = FollowerUnits(report=self._report)
        self._job_threads: List[threading.Thread] = []
        #: job_id -> live JobEntity, for leader progress queries
        self._entities: Dict[str, Any] = {}
        self._deferred_evals: Dict[str, Any] = {}  # job_id -> closure
        # job_id -> (config, executor_ids, chkp_root): what the collective
        # deferred eval rebuilds its evaluator from at shutdown
        self._job_confs: Dict[str, Any] = {}
        _send(self._sock, {"cmd": "JOIN", "pid": pid})

        from harmony_tpu.metrics.manager import MetricManager
        from harmony_tpu.runtime.master import ETMaster

        self.master = ETMaster()
        self.master.add_executors(num_executors)
        self.metrics = MetricManager()
        self.metrics.start_collection()
        # telemetry plane, follower leg: flight recorder capturing this
        # process's spans/events, and a per-process /metrics endpoint
        # (HARMONY_METRICS_PORT; None when unset)
        from harmony_tpu.metrics.exporter import exporter_from_env
        from harmony_tpu.tracing import flight as _flight

        _flight.get_recorder()
        self.metrics_exporter = exporter_from_env()
        # Liveness beacon: the leader gates its job-report waits on
        # heartbeat freshness (never job duration), so a follower whose
        # job threads are busy inside hours-long collectives must still
        # produce traffic. Dedicated daemon thread; dies silently with
        # the socket at shutdown.
        self._hb_period = float(os.environ.get("HARMONY_POD_HB_PERIOD",
                                               "5"))
        self._hb_stop = threading.Event()
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name=f"pod-hb-{pid}").start()

    def _heartbeat_loop(self) -> None:
        from harmony_tpu import faults

        while not self._hb_stop.wait(self._hb_period):
            try:
                jobs = sorted(self._entities)
            except RuntimeError:
                # a job thread resized the dict mid-iteration; the next
                # beat catches up — the beacon must NEVER die while the
                # process is healthy (its silence poisons the pod)
                continue
            if faults.armed():
                # injected heartbeat silence ("skip" drops this beat; a
                # "raise" rule is contained to the same outcome): the
                # process is alive but mute — exactly the partial
                # failure the leader's hb_timeout/infra-dead confinement
                # must handle. The beacon THREAD must survive any
                # injected action (its death would silence ALL beats,
                # violating the never-die invariant above).
                try:
                    if faults.site("pod.heartbeat", pid=self.pid) == "skip":
                        continue
                except Exception:
                    continue  # one beat lost, beacon lives
            try:
                # the beacon advertises this process's /metrics port so
                # the leader's history scraper discovers followers from
                # the heartbeat plumbing it already trusts (no separate
                # service registry); None when the exporter is off
                self._report({"cmd": "HEARTBEAT", "pid": self.pid,
                              "jobs": jobs,
                              "metrics_port": (
                                  self.metrics_exporter.port
                                  if self.metrics_exporter is not None
                                  else None)})
            except OSError:
                if self._reconnect:
                    # leader change in progress: the main loop's rejoin
                    # swaps the socket; the beacon must outlive the gap
                    # (its silence would confine this healthy follower)
                    continue
                return  # leader gone; the main loop handles shutdown

    def _report(self, payload: Dict[str, Any]) -> None:
        with self._send_lock:
            from harmony_tpu import faults

            if faults.armed():
                from harmony_tpu.faults.partition import frame_dropped

                # follower->leader link rule: an asymmetric partition
                # silences reports/heartbeats while leader->follower
                # commands still flow (the half-open link case)
                if frame_dropped(self._sock, role="pod.report"):
                    return
            _send(self._sock, payload)

    def _reject_stale(self, msg: Dict[str, Any], epoch: int) -> None:
        """A deposed leader's late message (its epoch is below the
        highest this follower has seen). RUN_JOB gets an explicit
        failure report keyed by ITS attempt so the stale leader's
        report wait resolves instead of hanging; everything else is
        dropped."""
        self.stale_rejected += 1
        server_log.warning(
            "follower %d: rejected stale-epoch %d %r (current leader "
            "epoch %d)", self.pid, epoch, msg.get("cmd"),
            self._leader_epoch)
        if msg.get("cmd") == "RUN_JOB":
            rkey = _elastic.attempt_key(
                str(msg.get("conf", {}).get("job_id", "?")),
                int(msg.get("att", 0) or 0))
            try:
                self._report({
                    "cmd": "JOB_DONE", "pid": self.pid, "job_id": rkey,
                    "ok": False, "stale_epoch": True,
                    "error": f"fenced: RUN_JOB from deposed leader "
                             f"epoch {epoch} < {self._leader_epoch}",
                })
            except OSError:
                pass

    def _rejoin(self) -> bool:
        """Leader-change re-HELLO: reconnect to the (possibly new)
        leader's control port and JOIN again under the SAME pid —
        executors, entities and running job threads all survive; the
        new leader's late-join path reinstates this follower. False
        when no leader answers within the join timeout (the pod is
        gone, not just its leader)."""
        deadline = time.monotonic() + self._join_timeout
        delay = 0.2
        while time.monotonic() < deadline:
            for host, port in self._leader_addrs:
                try:
                    from harmony_tpu.faults.partition import fault_connect

                    sock = fault_connect((host, port), role="pod.rejoin",
                                         timeout=5.0)
                except OSError:
                    continue
                sock.settimeout(None)
                f = sock.makefile("r")
                with self._send_lock:
                    old = self._sock
                    try:
                        _send(sock, {"cmd": "JOIN", "pid": self.pid})
                    except OSError:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        continue
                    self._sock = sock
                    self._file = f
                try:
                    old.close()
                except OSError:
                    pass
                server_log.info(
                    "follower %d re-HELLO'd leader at %s:%d after "
                    "connection loss (running jobs kept)",
                    self.pid, host, port)
                return True
            time.sleep(delay)
            delay = min(delay * 2, 2.0)
        server_log.error(
            "follower %d: no leader answered within %.0fs; shutting "
            "down", self.pid, self._join_timeout)
        return False

    def run(self) -> None:
        """Serve RUN_JOB commands until SHUTDOWN (or leader hangup).
        Each RUN_JOB executes on its own thread so concurrent jobs the
        leader admitted (disjoint process sets) truly overlap here."""
        from harmony_tpu.runtime.taskunit import GlobalTaskUnitScheduler

        global_tu = GlobalTaskUnitScheduler()
        # same platform-derived policy as JobServer.start: execution
        # metering is a blocking-backend concept; follower and leader
        # must agree or their grant policies diverge
        global_tu.meter_execution = all(
            self.master.executor(e).device.platform == "cpu"
            for e in self.master.executor_ids()
        )
        while True:
            try:
                msg = _recv(self._file)
            except (OSError, ValueError):
                msg = None
            if msg is None and self._reconnect and self._rejoin():
                continue  # leader change: re-HELLO'd the (new) leader
            if msg is not None:
                ep = msg.get("leader_epoch")
                if ep is not None:
                    ep = int(ep)
                    if ep < self._leader_epoch:
                        # fenced BEFORE any dispatch — including
                        # SHUTDOWN: a deposed leader's graceful exit
                        # must not tear down a follower that now
                        # belongs to its successor's pod
                        self._reject_stale(msg, ep)
                        continue
                    self._leader_epoch = ep
            if msg is None or msg.get("cmd") == "SHUTDOWN":
                for t in self._job_threads:
                    t.join(timeout=60.0)
                # The shutdown-stage deferred model evals for jobs this
                # follower chiefed (the leader is waiting on EVAL_DONE).
                for job_id, fn in list(self._deferred_evals.items()):
                    try:
                        result = _json_sanitize(fn(self.master))
                    except BaseException as e:  # noqa: BLE001 - reported
                        result = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        self._report({"cmd": "EVAL_DONE", "job_id": job_id,
                                      "result": result})
                    except OSError:
                        break  # leader gone; nothing to tell it
                self._hb_stop.set()
                if self.metrics_exporter is not None:
                    self.metrics_exporter.stop()
                    self.metrics_exporter = None
                self._sock.close()
                return
            if msg.get("cmd") == "TU_GRANT":
                self._pod_units.on_grant(
                    str(msg.get("job_id")), int(msg.get("seq", 0)),
                    bool(msg.get("contended", False)),
                )
                continue
            if msg.get("cmd") == "TU_POISON":
                self._pod_units.on_poison()
                continue
            if msg.get("cmd") == "PLAN":
                from harmony_tpu.jobserver import podplan

                podplan.schedule(msg["job_id"], msg["plan"])
                continue
            if msg.get("cmd") == "PROGRESS_REQ":
                # the leader's observed-epoch-floor query for plan/fence
                # validation (schedule_pod_reshard / elastic fences on
                # remote-only jobs). The query may arrive keyed by the
                # job id OR an elastic attempt key (jobserver/elastic):
                # entities register under attempt keys, so fall back to
                # the newest attempt of the requested job.
                jid = str(msg.get("job_id"))
                ent = self._entities.get(jid)
                if ent is None:
                    base = jid.split("@a", 1)[0]
                    for k in sorted(self._entities, reverse=True):
                        if k == base or k.startswith(base + "@a"):
                            ent = self._entities[k]
                            break
                ep = 0
                if ent is not None and getattr(ent, "progress", None) is not None:
                    ep = ent.progress.starting_epoch()
                self._report({"cmd": "PROGRESS_REP", "job_id": jid,
                              "epoch": int(ep)})
                continue
            if msg.get("cmd") == "EVAL_COLLECTIVE":
                # the leader's deferred model eval is a lockstep collective
                # (restore + evaluate over the multi-process mesh): run the
                # identical evaluation here, inline (shutdown-stage; no
                # jobs are running), then ack
                self._run_collective_eval(msg)
                continue
            assert msg.get("cmd") == "RUN_JOB", msg
            t = threading.Thread(
                target=self._run_job, args=(msg, global_tu), daemon=True,
                name=f"pod-job-{msg.get('conf', {}).get('job_id', '?')}",
            )
            self._job_threads = [x for x in self._job_threads if x.is_alive()]
            self._job_threads.append(t)
            t.start()

    def _run_collective_eval(self, msg: Dict[str, Any]) -> None:
        """The follower leg of the pod-collective deferred model eval:
        rebuild the SAME trainer/test-data/checkpoint-manager the leader's
        closure resolves (everything derives from the job config, which
        lockstep already requires be identical) and replay the chain —
        the restores and evaluate steps join the leader's collectives.
        Results are discarded (identical to the leader's, which records
        them); the ack unblocks the leader's bounded wait."""
        job_id = str(msg.get("job_id"))
        ready = {"cmd": "EVAL_COLLECTIVE_READY", "pid": self.pid,
                 "job_id": job_id, "ok": False}
        staged = None
        try:
            config, executor_ids, chkp_root = self._job_confs[job_id]
            from harmony_tpu.checkpoint.manager import CheckpointManager
            from harmony_tpu.dolphin.evaluator import (
                ModelEvaluator,
                resolve_eval_inputs,
            )

            # HOST-ONLY staging before the readiness ack: anything that
            # can fail must fail HERE, where aborting is clean — once the
            # collectives start, a one-sided failure wedges the pod
            mgr = CheckpointManager.for_job(chkp_root, job_id)
            trainer, batch = resolve_eval_inputs(config)  # the SHARED
            # resolution — byte-identical collectives with the leader
            staged = (mgr, trainer, batch, executor_ids)
            ready["ok"] = True
        except BaseException as e:  # noqa: BLE001 - acked to leader
            ready["error"] = f"{type(e).__name__}: {e}"
        self._report(ready)
        # the leader decides GO (all ready) or ABORT (anyone failed —
        # including this process); only GO enters the collectives
        decision = _recv(self._file)
        if not decision or decision.get("cmd") != "EVAL_GO":
            return  # aborted (or leader hung up): nothing dispatched
        report = {"cmd": "EVAL_COLLECTIVE_DONE", "pid": self.pid,
                  "job_id": job_id, "ok": False}
        try:
            mgr, trainer, batch, executor_ids = staged
            ModelEvaluator(self.master, mgr).evaluate_checkpoints(
                list(msg.get("chkp_ids", [])), trainer, batch, executor_ids
            )
            report["ok"] = True
        except BaseException as e:  # noqa: BLE001 - acked to leader
            report["error"] = f"{type(e).__name__}: {e}"
        self._report(report)

    def _run_job(self, msg: Dict[str, Any], global_tu) -> None:
        """One span per follower job leg, re-parented onto the leader's
        dispatch span via the RUN_JOB trace context — the cross-PROCESS
        half of the submission trace. The job thread has no ambient span,
        so the explicit parent is the only way the legs connect."""
        rkey = _elastic.attempt_key(
            str(msg.get("conf", {}).get("job_id", "?")),
            int(msg.get("att", 0) or 0),
        )
        with trace_span(
            "pod.follower_job",
            parent=SpanContext.from_wire(msg.get("trace")),
            job_id=msg.get("conf", {}).get("job_id"),
            attempt=rkey,
            pid=self.pid,
        ):
            self._run_job_inner(msg, global_tu)

    def _run_job_inner(self, msg: Dict[str, Any], global_tu) -> None:
        from harmony_tpu.jobserver.entity import build_entity
        from harmony_tpu.runtime.taskunit import LocalTaskUnitScheduler

        config = ConfigBase.from_dict(msg["conf"])
        executor_ids = msg["executor_ids"]
        if (config.params.offline_model_eval
                and config.params.model_chkp_period > 0):
            # retained for the shutdown-stage collective eval — ONLY for
            # jobs that will run one (unconditional retention would let
            # unrelated jobs evict a config still needed at shutdown)
            self._job_confs[config.job_id] = (
                config, list(executor_ids), msg.get("chkp_root")
            )
            while len(self._job_confs) > 1024:
                self._job_confs.pop(next(iter(self._job_confs)))
        chief = int(msg.get("chief_pid", 0)) == self.pid
        # elastic attempt key: report routing, the entity registry and
        # the unit protocol are all attempt-scoped so a superseded
        # attempt's stragglers can never be misattributed to a live one
        rkey = _elastic.attempt_key(config.job_id,
                                    int(msg.get("att", 0) or 0))
        report: Dict[str, Any] = {
            "cmd": "JOB_DONE", "pid": self.pid, "job_id": rkey,
        }
        unit_extras: Dict[str, Any] = {}
        if msg.get("pod_ordered"):
            # this process's leg of the cross-job unit protocol (the
            # leader's arbiter orders overlapping tenants' dispatches)
            client = follower_client(self._pod_units, rkey)
            unit_extras = {"pod_unit_scope": client.scope,
                           "pod_unit_contended": client.contended}
        entity = None
        try:
            missing = set(executor_ids) - set(self.master.executor_ids())
            if missing:
                raise RuntimeError(
                    f"follower {self.pid} missing executors {missing} "
                    "(leader/follower allocation orders diverged)"
                )
            # Mirror the leader's entity EXACTLY (see RUN_JOB comment):
            # same taskunit phasing, a local metric pipeline of our own.
            entity = build_entity(
                config,
                global_taskunit=global_tu,
                local_taskunit=LocalTaskUnitScheduler(
                    msg.get("cpu_slots", 1), msg.get("net_slots", 2)
                ),
                metric_sink=self.metrics.on_metric,
                metric_manager=self.metrics,
                chkp_root=msg.get("chkp_root"),
                **unit_extras,
            )
            self._entities[rkey] = entity
            entity.setup(self.master, executor_ids)
            result = entity.run()
            if chief:
                # Deferred model evaluation is registered BEFORE cleanup
                # drops the tables (the eval replays checkpoints from
                # disk); it runs at SHUTDOWN, exactly like the leader's
                # _run_deferred_evals stage. Chief-only: one eval per job.
                deferred = entity.deferred_evaluation()
                if deferred is not None:
                    self._deferred_evals[config.job_id] = deferred
                    report["has_deferred_eval"] = True
            entity.cleanup()
            report["ok"] = True
            report["workers"] = {
                wid: {
                    "losses": [float(x) for x in w.get("losses", [])],
                    # exactly-once evidence for elastic recovery tests:
                    # attempts' epoch ranges must tile [0, num_epochs)
                    "starting_epoch": int(w.get("starting_epoch", 0)),
                    "epochs_run": int(w.get("epochs_run",
                                            len(w.get("losses", [])))),
                }
                for wid, w in result.get("workers", {}).items()
            }
            if chief:
                # The chief's result resolves the leader's job future when
                # the leader holds none of the job's devices.
                report["result"] = _json_sanitize(result)
        except BaseException as e:  # noqa: BLE001 - reported to leader
            # Cleanup on failure, like the leader's _dispatch error path:
            # a leaked table would make every resubmission of this job_id
            # fail on this follower with "table exists".
            if entity is not None:
                try:
                    entity.cleanup()
                except Exception:
                    pass
            report["ok"] = False
            report["error"] = f"{type(e).__name__}: {e}"
            try:  # black-box trail: the failure beside its recent spans
                from harmony_tpu.tracing import flight

                flight.get_recorder().event(
                    "follower_job_failed", job=rkey, pid=self.pid,
                    error=f"{type(e).__name__}: {e}"[:300],
                    elastic_fence=str(getattr(e, "elastic_fence", "") or ""),
                )
            except Exception:
                pass
            if getattr(e, "elastic_fence", None):
                # a planned elastic fence, not a failure of the job's
                # own logic: the leader's elastic loop classifies on
                # this marker and continues the SAME submission
                report["elastic_fence"] = str(e.elastic_fence)
            if getattr(e, "infra_suspect", False):
                # a bounded-retry give-up (transport/storage/helper died
                # — faults.retry.InfraTransientError): tell the leader
                # this failure is INFRA-shaped so auto_resume jobs are
                # eligible to resubmit, exactly like a follower death
                report["infra_suspect"] = True
        self._entities.pop(rkey, None)
        self._pod_units.forget(rkey)
        self._report(report)
