"""Multi-host JobServer — the driver/evaluator split over real processes.

The reference's JobServer is a driver PROCESS coordinating remote evaluator
JVMs (ref: jobserver/src/main/java/edu/snu/cay/jobserver/driver/
JobServerDriver.java:149-163, ResourcePool.java:73-81). The TPU-pod
equivalent keeps the same split with JAX's multi-controller SPMD model:

  * every host process joins one ``jax.distributed`` runtime
    (parallel/multihost.py), after which ``jax.devices()`` is the GLOBAL
    chip list on all of them;
  * process 0 runs the :class:`PodJobServer` — the ordinary JobServer
    (scheduling, registry, TCP submit endpoint) plus a pod control plane;
  * every other process runs a :class:`PodFollower` loop.

Control plane (DCN, JSON-over-TCP — same framing as client.py): followers
JOIN the leader; for each dispatched job the leader broadcasts RUN_JOB with
the serialized JobConfig and executor grant, every process builds the SAME
JobEntity and runs it, and the jitted train steps inside are global-mesh
SPMD programs — their XLA collectives (ICI/DCN) are the data plane and the
de-facto barrier, exactly the reference's msg-plus-collective split
(SURVEY.md §5.8). At job end followers report JOB_DONE with their local
worker metrics, which the leader records per process id — the cross-process
metric flow the reference routes through its MetricManager msg senders.

Determinism contract (what makes lockstep correct): entity construction is
a pure function of the JobConfig, executor ids are allocated by a fresh
per-process counter in identical order, and synthetic/file data loading is
seeded — so all processes issue the same global computations in the same
order. Pod jobs are serialized by the leader (one RUN_JOB at a time): two
concurrently-dispatched jobs would interleave their collectives in
process-dependent order and deadlock the mesh.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from harmony_tpu.config.base import ConfigBase
from harmony_tpu.config.params import JobConfig
from harmony_tpu.jobserver.joblog import job_logger, server_log
from harmony_tpu.jobserver.server import JobServer


def _send(sock: socket.socket, msg: Dict[str, Any]) -> None:
    sock.sendall((json.dumps(msg) + "\n").encode())


def _recv(f) -> Optional[Dict[str, Any]]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class PodJobServer(JobServer):
    """JobServer on process 0 of a pod: adds the follower control plane."""

    def __init__(self, *args, num_followers: int = 0, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._num_followers = num_followers
        self._pod_sock: Optional[socket.socket] = None
        self._followers: Dict[int, Any] = {}  # pid -> (sock, reader file)
        self._pod_lock = threading.Lock()  # serializes pod job execution
        # A partially-delivered RUN_JOB leaves the followers that DID
        # receive it blocked in global collectives (XLA collectives do not
        # time out); no later job can run on this pod. The flag fails all
        # subsequent pod dispatches fast instead of hanging them.
        self._pod_broken: Optional[str] = None
        #: job_id -> {pid: follower JOB_DONE payload}
        self.pod_reports: Dict[str, Dict[int, Dict[str, Any]]] = {}

    # -- follower management --------------------------------------------

    def serve_pod(self, port: int = 0, join_timeout: float = 300.0) -> int:
        """Listen for follower JOINs; blocks until all ``num_followers``
        processes have joined (startup is a pod-wide barrier — dispatching
        before the pod is whole would hang the first collective anyway)."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("0.0.0.0", port))
        sock.listen(16)
        self._pod_sock = sock
        bound = sock.getsockname()[1]
        sock.settimeout(join_timeout)
        while len(self._followers) < self._num_followers:
            try:
                conn, addr = sock.accept()
            except socket.timeout:
                raise TimeoutError(
                    f"pod join: {len(self._followers)}/{self._num_followers} "
                    f"followers after {join_timeout}s"
                )
            # accept()'d sockets are BLOCKING regardless of the listener's
            # timeout: a connection that never sends JOIN (health check,
            # scanner, crashed follower) must not hang bootstrap forever
            conn.settimeout(30.0)
            f = conn.makefile("r")
            try:
                hello = _recv(f)
                # garbage (an HTTP health check, a scanner) or a JOIN with
                # no pid must be dropped like silence, not crash bootstrap
                pid = int(hello["pid"]) if hello else None
            except (socket.timeout, OSError, ValueError, KeyError, TypeError):
                hello, pid = None, None
            if not hello or hello.get("cmd") != "JOIN" or pid is None:
                conn.close()
                continue
            conn.settimeout(None)  # RUN_JOB/JOB_DONE set their own deadlines
            self._followers[pid] = (conn, f)
            server_log.info("pod follower %d joined from %s", pid, addr)
        return bound

    def _broadcast(self, msg: Dict[str, Any]) -> None:
        for pid, (conn, _) in sorted(self._followers.items()):
            _send(conn, msg)

    def _collect_done(self, job_id: str, timeout: float) -> Dict[int, Dict[str, Any]]:
        """One JOB_DONE per follower; a silent follower is recorded as an
        error entry rather than wedging the leader forever. A stale report
        from an earlier job (its collection timed out; the follower finished
        late) is skipped, never attributed to this job."""
        deadline = time.monotonic() + timeout
        out: Dict[int, Dict[str, Any]] = {}
        for pid, (conn, f) in sorted(self._followers.items()):
            while pid not in out:
                try:
                    conn.settimeout(max(0.1, deadline - time.monotonic()))
                    msg = _recv(f)
                except (socket.timeout, OSError) as e:
                    # "infra" marks leader-observed transport failures
                    # (timeout/hangup) — the follower is gone or wedged —
                    # as opposed to a follower-REPORTED job error, after
                    # which the follower is alive and serviceable.
                    out[pid] = {"ok": False, "infra": True,
                                "error": f"follower read: {e}"}
                    continue
                if msg is None:
                    out[pid] = {"ok": False, "infra": True,
                                "error": "follower closed connection"}
                elif msg.get("job_id") == job_id:
                    out[pid] = msg
                else:  # stale report from a timed-out earlier collection
                    server_log.warning(
                        "pod: dropping stale report from follower %d "
                        "(job %s, collecting %s)",
                        pid, msg.get("job_id"), job_id,
                    )
        return out

    # -- dispatch override ------------------------------------------------

    def _fail_job(self, config: JobConfig, error: str) -> None:
        jr = self._jobs[config.job_id]
        jr.future.set_exception(RuntimeError(error))
        self._scheduler.on_job_finish(config.job_id)

    def _status(self) -> Dict[str, Any]:
        out = super()._status()
        out["pod"] = {
            "followers": sorted(self._followers),
            "broken": self._pod_broken,
        }
        return out

    def submit(self, config: JobConfig):
        # Rejected HERE so TCP submitters see {"ok": false, error} instead
        # of an ok-then-vanished job. num_workers=0 (the CLI default,
        # "one per granted executor") is included when the pool holds more
        # than one executor — the default scheduler grants them all, so 0
        # resolves to >1 dispatch threads. (A 1-executor pod legally runs
        # 0; the dispatch-time effective check stays as ground truth.)
        if self._num_followers and (
            config.num_workers > 1
            or (config.num_workers == 0 and self._num_executors > 1)
        ):
            raise ValueError(
                f"pod jobs need num_workers=1 (got "
                f"{config.num_workers}; 0 means one per executor): the "
                "SPMD lockstep contract cannot hold across multiple "
                "dispatch threads — submit with --workers 1"
            )
        return super().submit(config)

    def _dispatch(self, config: JobConfig, executor_ids: List[str]) -> None:
        with self._pod_lock:  # one pod job at a time (see module doc)
            effective_workers = config.num_workers or len(executor_ids)
            if self._followers and effective_workers != 1:
                # >1 worker per process = N dispatch threads whose host
                # scheduling differs across processes -> divergent global
                # enqueue order -> collective mismatch. Reject loudly
                # instead of wedging the pod.
                self._fail_job(
                    config,
                    f"pod jobs need one dispatch thread, got "
                    f"num_workers={config.num_workers} over "
                    f"{len(executor_ids)} executors: the SPMD lockstep "
                    "contract cannot hold across multiple dispatch threads",
                )
                return
            if self._followers and self._pod_broken:
                self._fail_job(
                    config,
                    f"pod is broken ({self._pod_broken}); restart the pod "
                    "processes — followers may be wedged in collectives",
                )
                return
            if self._followers:
                job_logger(config.job_id).info(
                    "pod: broadcasting RUN_JOB to %d follower(s)",
                    len(self._followers),
                )
                try:
                    self._broadcast({
                        "cmd": "RUN_JOB",
                        "conf": config.to_dict(),
                        "executor_ids": list(executor_ids),
                        # Followers must build the entity with the SAME aux
                        # components: the TaskUnit schedulers change how the
                        # worker phases its device dispatches (fused vs
                        # split PULL/COMP/PUSH), and any asymmetry there is
                        # a cross-process collective mismatch.
                        "cpu_slots": self.local_taskunit.cpu_slots,
                        "net_slots": self.local_taskunit.net_slots,
                    })
                except OSError as e:
                    # A partially-delivered RUN_JOB cannot train (the SPMD
                    # collectives need every process), and base _dispatch's
                    # guarantees live inside ITS try-block — so fail the
                    # job the way the base error path would, and POISON the
                    # pod: followers that did get the message are now
                    # blocked in collectives no later job can satisfy.
                    self._pod_broken = f"RUN_JOB broadcast failed: {e}"
                    server_log.error("pod broken: %s", self._pod_broken)
                    self._fail_job(
                        config, f"pod RUN_JOB broadcast failed: {e}"
                    )
                    return
            super()._dispatch(config, executor_ids)
            if self._followers:
                try:
                    reports = self._collect_done(config.job_id, timeout=600.0)
                except Exception as e:  # noqa: BLE001 - job already resolved
                    reports = {"error": f"report collection failed: {e}"}
                # A follower that never reported is wedged (likely stuck in
                # a collective): the next RUN_JOB's collectives could never
                # complete — poison the pod like the broadcast-failure path.
                dead = [pid for pid, r in reports.items()
                        if isinstance(r, dict) and r.get("infra")]
                if dead:
                    self._pod_broken = (
                        f"follower(s) {dead} never reported for "
                        f"{config.job_id}"
                    )
                    server_log.error("pod broken: %s", self._pod_broken)
                self.pod_reports[config.job_id] = reports
                while len(self.pod_reports) > 256:  # bound leader memory
                    self.pod_reports.pop(next(iter(self.pod_reports)))

    def shutdown(self, timeout: Optional[float] = 300.0) -> None:
        super().shutdown(timeout)
        # The job futures resolve BEFORE follower reports are collected, so
        # a client reacting to job completion can reach shutdown while
        # _dispatch is still reading JOB_DONEs; taking the pod lock here
        # orders the socket teardown after that collection.
        with self._pod_lock:
            pass
        if self._followers:
            try:
                self._broadcast({"cmd": "SHUTDOWN"})
            except OSError:
                pass
            for conn, f in self._followers.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._followers.clear()
        if self._pod_sock is not None:
            self._pod_sock.close()
            self._pod_sock = None


class PodFollower:
    """Evaluator-side loop on processes 1..N-1 of a pod.

    Mirrors the leader's job lifecycle against a local ETMaster whose
    executor ids — produced by the same fresh-process allocation order —
    name the same global devices as the leader's."""

    def __init__(self, leader_host: str, pod_port: int, pid: int,
                 num_executors: int, join_timeout: float = 300.0) -> None:
        self.pid = pid
        # The leader may still be initializing its runtime when followers
        # come up (hosts boot in any order): retry until the deadline.
        deadline = time.monotonic() + join_timeout
        while True:
            try:
                self._sock = socket.create_connection(
                    (leader_host, pod_port), timeout=10.0
                )
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self._sock.settimeout(None)  # RUN_JOB may arrive much later
        self._file = self._sock.makefile("r")
        _send(self._sock, {"cmd": "JOIN", "pid": pid})

        from harmony_tpu.metrics.manager import MetricManager
        from harmony_tpu.runtime.master import ETMaster

        self.master = ETMaster()
        self.master.add_executors(num_executors)
        self.metrics = MetricManager()
        self.metrics.start_collection()

    def run(self) -> None:
        """Serve RUN_JOB commands until SHUTDOWN (or leader hangup)."""
        from harmony_tpu.jobserver.entity import build_entity
        from harmony_tpu.runtime.taskunit import (
            GlobalTaskUnitScheduler,
            LocalTaskUnitScheduler,
        )

        global_tu = GlobalTaskUnitScheduler()
        while True:
            msg = _recv(self._file)
            if msg is None or msg.get("cmd") == "SHUTDOWN":
                self._sock.close()
                return
            assert msg.get("cmd") == "RUN_JOB", msg
            config = ConfigBase.from_dict(msg["conf"])
            executor_ids = msg["executor_ids"]
            report: Dict[str, Any] = {
                "cmd": "JOB_DONE", "pid": self.pid, "job_id": config.job_id,
            }
            try:
                missing = set(executor_ids) - set(self.master.executor_ids())
                if missing:
                    raise RuntimeError(
                        f"follower {self.pid} missing executors {missing} "
                        "(leader/follower allocation orders diverged)"
                    )
                # Mirror the leader's entity EXACTLY (see RUN_JOB comment):
                # same taskunit phasing, a local metric pipeline of our own.
                entity = build_entity(
                    config,
                    global_taskunit=global_tu,
                    local_taskunit=LocalTaskUnitScheduler(
                        msg.get("cpu_slots", 1), msg.get("net_slots", 2)
                    ),
                    metric_sink=self.metrics.on_metric,
                    metric_manager=self.metrics,
                )
                entity.setup(self.master, executor_ids)
                result = entity.run()
                entity.cleanup()
                report["ok"] = True
                report["workers"] = {
                    wid: {"losses": [float(x) for x in w.get("losses", [])]}
                    for wid, w in result.get("workers", {}).items()
                }
            except BaseException as e:  # noqa: BLE001 - reported to leader
                report["ok"] = False
                report["error"] = f"{type(e).__name__}: {e}"
            _send(self._sock, report)
