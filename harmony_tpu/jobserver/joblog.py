"""Per-job prefixed operator logging.

Rebuilds the reference's JobLogger (ref: jobserver/src/main/java/edu/snu/
cay/jobserver/JobLogger.java:34-75): a multi-tenant server interleaves many
jobs' lifecycle events in one operator log, so every job-scoped line carries
a ``[JobId: <id>]`` prefix. The reference injects a JobLogger per job via
Tang and re-infers the caller frame by hand; here the analogue is a
``logging.LoggerAdapter`` over the shared ``harmony_tpu.jobserver`` logger —
stdlib logging already records the caller, handlers/levels stay configurable
by the host application, and the adapter is cheap enough to create per job.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

#: Shared base logger for server-scoped (not job-scoped) events.
server_log = logging.getLogger("harmony_tpu.jobserver")

# -- structured recovery/lifecycle events ---------------------------------
#
# Free-text operator logs are unqueryable; the recovery paths (elastic
# shrink/re-grow, confinement, rehabilitation, auto-resume) additionally
# record STRUCTURED events here so the job status JSON and the dashboard
# can surface them without log scraping. Per-process, bounded, in-memory
# — the durable record is still the operator log.

#: Declared event-kind catalog (the doctor_rule precedent, applied to
#: the event stream itself): every ``kind=`` a production module passes
#: to :func:`record_event` / :meth:`JobLogger.event` — including the
#: flight-ring-only evidence kinds — is declared here with its emitter
#: and meaning. The ``event-kind-registry`` harmonylint pass enforces
#: two-way parity between this catalog, the literal kinds emitted in
#: code, and the event-kind table in docs/OBSERVABILITY.md §10 — an
#: undeclared kind is invisible to the incident engine's role
#: classification (metrics/incidents.py) and to operators grepping the
#: docs. Dynamic kinds (the elastic f-strings) are declared per
#: expansion.
EVENT_KINDS: Dict[str, str] = {
    "slo": "dolphin/worker.py: per-epoch SLO attainment sample",
    "serving_slo": "serving/service.py: windowed serving p99 over the "
                   "tenant's latency objective",
    "process_restart": "metrics/history.py: scrape-target process "
                       "restart detected (counter reset)",
    "diagnosis": "metrics/doctor.py: structured doctor verdict",
    "leader_takeover": "jobserver/ha.py: HA leader transition",
    "overload": "jobserver/overload.py: control-plane ladder move",
    "policy": "jobserver/policy.py: device policy action (advised or "
              "acted)",
    "elastic_restore": "jobserver/entity.py: elastic attempt restored "
                       "from checkpoint",
    "elastic_give_up": "jobserver/pod.py: elastic retry budget "
                       "exhausted",
    "follower_silenced": "jobserver/pod.py: flapping follower confined",
    "follower_rehabilitated": "jobserver/pod.py: confined follower "
                              "readmitted",
    "elastic_shrink": "jobserver/pod.py: attempt shrunk around a death",
    "elastic_regrow": "jobserver/pod.py: attempt regrown onto "
                      "recovered workers",
    "elastic_shrink_fence": "jobserver/pod.py: lockstep fence for a "
                            "shrink scheduled",
    "elastic_regrow_fence": "jobserver/pod.py: lockstep fence for a "
                            "regrow scheduled",
    "chkp_chain": "checkpoint/manager.py: chained checkpoint committed",
    "incident": "metrics/incidents.py: incident lifecycle transition "
                "(open/mitigating/resolved)",
    "fault_trip": "tracing/flight.py: fault-injection site fired "
                  "(flight ring)",
    "follower_death": "jobserver/pod.py: follower death observed "
                      "(flight ring)",
    "follower_job_failed": "jobserver/pod.py: follower-side job "
                           "failure (flight ring)",
}

_EVENTS_LOCK = threading.Lock()
_EVENTS: Dict[str, List[Dict[str, Any]]] = {}
_EVENTS_PER_JOB = 64
_EVENTS_MAX_JOBS = 256
#: durable sinks (jobserver/halog.py): every structured event tees here
#: so control-plane transitions reach the replicated on-disk log. Sinks
#: must never fail the recording path.
_SINKS: List[Any] = []


def record_event(job_id: str, kind: str, **fields: Any) -> Dict[str, Any]:
    """Append one structured event to ``job_id``'s ring. ``fields`` must
    be JSON-serializable (they ride the status endpoint verbatim).

    Eviction is least-recently-APPENDED: re-inserting the ring under its
    key on every append keeps dict order = activity order, so the jobs
    popped at the cap are the ones longest silent — a long-lived busy
    job can no longer be evicted while dead jobs linger (the old loop
    popped in plain insertion order)."""
    ev = {"ts": time.time(), "kind": kind, **fields}
    with _EVENTS_LOCK:
        ring = _EVENTS.pop(job_id, None)
        if ring is None:
            ring = []
        ring.append(ev)
        del ring[:-_EVENTS_PER_JOB]
        _EVENTS[job_id] = ring  # re-insert: now the most recently active
        while len(_EVENTS) > _EVENTS_MAX_JOBS:
            _EVENTS.pop(next(iter(_EVENTS)))
        sinks = list(_SINKS)
    for sink in sinks:
        try:
            sink(job_id, ev)
        except Exception:
            pass  # durability tee must never fail the event path
    return ev


def add_sink(fn) -> None:
    """Register a ``fn(job_id, event_dict)`` tee on every recorded
    event (the HA durable log registers here)."""
    with _EVENTS_LOCK:
        if fn not in _SINKS:
            _SINKS.append(fn)


def remove_sink(fn) -> None:
    with _EVENTS_LOCK:
        if fn in _SINKS:
            _SINKS.remove(fn)


def job_events(job_id: Optional[str] = None,
               limit: int = 32) -> "Dict[str, List[Dict[str, Any]]] | List[Dict[str, Any]]":
    """Recorded events — for one job (a list, newest last) or all jobs
    (job_id -> list). Snapshots; mutation-safe for callers."""
    with _EVENTS_LOCK:
        if job_id is not None:
            return list(_EVENTS.get(job_id, []))[-limit:]
        return {j: list(evs)[-limit:] for j, evs in _EVENTS.items()}


def clear_events(job_id: Optional[str] = None) -> None:
    with _EVENTS_LOCK:
        if job_id is None:
            _EVENTS.clear()
        else:
            _EVENTS.pop(job_id, None)


class JobLogger(logging.LoggerAdapter):
    """Logger whose every message is prefixed with the owning job's id."""

    def __init__(self, job_id: str, logger: logging.Logger | None = None) -> None:
        super().__init__(logger or server_log, {"job_id": job_id})
        self.job_id = job_id

    def process(self, msg, kwargs):
        return f"[JobId: {self.job_id}] {msg}", kwargs

    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Structured event + the matching operator-log line in one call
        (the recovery paths' idiom: nothing important is ever ONLY in
        free text)."""
        self.info("%s %s", kind,
                  " ".join(f"{k}={v!r}" for k, v in sorted(fields.items())))
        return record_event(self.job_id, kind, **fields)


def job_logger(job_id: str) -> JobLogger:
    """The per-job logger factory (the Tang-injection analogue)."""
    return JobLogger(job_id)
