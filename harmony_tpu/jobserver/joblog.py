"""Per-job prefixed operator logging.

Rebuilds the reference's JobLogger (ref: jobserver/src/main/java/edu/snu/
cay/jobserver/JobLogger.java:34-75): a multi-tenant server interleaves many
jobs' lifecycle events in one operator log, so every job-scoped line carries
a ``[JobId: <id>]`` prefix. The reference injects a JobLogger per job via
Tang and re-infers the caller frame by hand; here the analogue is a
``logging.LoggerAdapter`` over the shared ``harmony_tpu.jobserver`` logger —
stdlib logging already records the caller, handlers/levels stay configurable
by the host application, and the adapter is cheap enough to create per job.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

#: Shared base logger for server-scoped (not job-scoped) events.
server_log = logging.getLogger("harmony_tpu.jobserver")

# -- structured recovery/lifecycle events ---------------------------------
#
# Free-text operator logs are unqueryable; the recovery paths (elastic
# shrink/re-grow, confinement, rehabilitation, auto-resume) additionally
# record STRUCTURED events here so the job status JSON and the dashboard
# can surface them without log scraping. Per-process, bounded, in-memory
# — the durable record is still the operator log.

_EVENTS_LOCK = threading.Lock()
_EVENTS: Dict[str, List[Dict[str, Any]]] = {}
_EVENTS_PER_JOB = 64
_EVENTS_MAX_JOBS = 256


def record_event(job_id: str, kind: str, **fields: Any) -> Dict[str, Any]:
    """Append one structured event to ``job_id``'s ring. ``fields`` must
    be JSON-serializable (they ride the status endpoint verbatim)."""
    ev = {"ts": time.time(), "kind": kind, **fields}
    with _EVENTS_LOCK:
        ring = _EVENTS.setdefault(job_id, [])
        ring.append(ev)
        del ring[:-_EVENTS_PER_JOB]
        while len(_EVENTS) > _EVENTS_MAX_JOBS:
            _EVENTS.pop(next(iter(_EVENTS)))
    return ev


def job_events(job_id: Optional[str] = None,
               limit: int = 32) -> "Dict[str, List[Dict[str, Any]]] | List[Dict[str, Any]]":
    """Recorded events — for one job (a list, newest last) or all jobs
    (job_id -> list). Snapshots; mutation-safe for callers."""
    with _EVENTS_LOCK:
        if job_id is not None:
            return list(_EVENTS.get(job_id, []))[-limit:]
        return {j: list(evs)[-limit:] for j, evs in _EVENTS.items()}


def clear_events(job_id: Optional[str] = None) -> None:
    with _EVENTS_LOCK:
        if job_id is None:
            _EVENTS.clear()
        else:
            _EVENTS.pop(job_id, None)


class JobLogger(logging.LoggerAdapter):
    """Logger whose every message is prefixed with the owning job's id."""

    def __init__(self, job_id: str, logger: logging.Logger | None = None) -> None:
        super().__init__(logger or server_log, {"job_id": job_id})
        self.job_id = job_id

    def process(self, msg, kwargs):
        return f"[JobId: {self.job_id}] {msg}", kwargs

    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Structured event + the matching operator-log line in one call
        (the recovery paths' idiom: nothing important is ever ONLY in
        free text)."""
        self.info("%s %s", kind,
                  " ".join(f"{k}={v!r}" for k, v in sorted(fields.items())))
        return record_event(self.job_id, kind, **fields)


def job_logger(job_id: str) -> JobLogger:
    """The per-job logger factory (the Tang-injection analogue)."""
    return JobLogger(job_id)
