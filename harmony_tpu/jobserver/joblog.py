"""Per-job prefixed operator logging.

Rebuilds the reference's JobLogger (ref: jobserver/src/main/java/edu/snu/
cay/jobserver/JobLogger.java:34-75): a multi-tenant server interleaves many
jobs' lifecycle events in one operator log, so every job-scoped line carries
a ``[JobId: <id>]`` prefix. The reference injects a JobLogger per job via
Tang and re-infers the caller frame by hand; here the analogue is a
``logging.LoggerAdapter`` over the shared ``harmony_tpu.jobserver`` logger —
stdlib logging already records the caller, handlers/levels stay configurable
by the host application, and the adapter is cheap enough to create per job.
"""
from __future__ import annotations

import logging

#: Shared base logger for server-scoped (not job-scoped) events.
server_log = logging.getLogger("harmony_tpu.jobserver")


class JobLogger(logging.LoggerAdapter):
    """Logger whose every message is prefixed with the owning job's id."""

    def __init__(self, job_id: str, logger: logging.Logger | None = None) -> None:
        super().__init__(logger or server_log, {"job_id": job_id})
        self.job_id = job_id

    def process(self, msg, kwargs):
        return f"[JobId: {self.job_id}] {msg}", kwargs


def job_logger(job_id: str) -> JobLogger:
    """The per-job logger factory (the Tang-injection analogue)."""
    return JobLogger(job_id)
