"""Elastic shrink-to-survivors recovery — shared vocabulary.

The capstone of the recovery matrix (docs/FAULT_TOLERANCE.md):
fail-fast -> auto-resume -> **degrade in place**. A job flagged
``user.elastic_shrink`` does not FAIL when a pod follower is lost — the
leader keeps the SAME submission (same job id, same client future) and
re-dispatches it over the survivor set, restoring only the lost blocks
from the last committed chain checkpoint (O(lost bytes), not O(model
bytes) — checkpoint/manager.restore_partial + the per-process recovery
cache). The reverse leg re-grows: when a lost follower comes back (a
replacement JOIN, or a silence-confined follower's heartbeats resuming),
the running shrunk job is fenced once and re-dispatched over the
restored executor set. Elastic-PS systems show the same contract —
degrade capacity in place and keep making progress (arXiv:2204.03211) —
and TPU-pod practice treats worker-count changes as scheduling events,
not job failures (arXiv:2011.03641).

Why a FENCE instead of a live in-flight reshard: the recovery point must
be a consistent epoch cut (the acceptance bar is numeric loss parity
with an uninterrupted run, every batch processed exactly once per
epoch). Mid-epoch in-flight state is not a cut — so reconfiguration
always lands at the chief worker's epoch hook, the one point lockstep
guarantees every participating process reaches at the same logical
epoch (jobserver/podplan.py). The fence rides the existing PLAN
broadcast; the chain hook has already snapshotted the fence epoch when
it fires (hook composition order in entity.run), so the re-dispatch
resumes exactly one epoch later with nothing lost and nothing replayed.

This module holds only the pieces BOTH sides (the pod control plane and
the job entity) need, so neither imports the other for them.
"""
from __future__ import annotations

import os


class ElasticFence(RuntimeError):
    """Raised by the chief worker's epoch hook when a fence plan is due:
    a deliberate, lockstep teardown of the current attempt so the leader
    can re-dispatch the same submission over a different executor set.
    NOT a failure of the job's own logic and NOT infra damage — the
    elastic dispatch loop catches it and continues the submission."""

    def __init__(self, kind: str, epoch: int) -> None:
        super().__init__(
            f"elastic {kind} fence at epoch {epoch}: attempt ends here so "
            "the same submission can continue on a different executor set"
        )
        self.kind = kind          # "shrink" | "regrow"
        self.epoch = int(epoch)

    @property
    def elastic_fence(self) -> str:
        """Marker attribute mirrored onto follower JOB_DONE reports and
        leader-side wrapper errors, so the elastic loop can classify a
        fence without importing concrete exception types across the
        wire."""
        return self.kind


def attempt_key(job_id: str, attempt: int) -> str:
    """Wire/report/unit-protocol key of one elastic attempt. Attempt 0 is
    the plain job id so every non-elastic path is byte-identical to
    before; recovery attempts get a suffix so stale reports, heartbeat
    listings and TaskUnit messages from a superseded attempt can never
    be misattributed to the live one."""
    return job_id if attempt <= 0 else f"{job_id}@a{attempt}"


def attempt_of(config) -> int:
    """The attempt index a (possibly recovery-) JobConfig encodes."""
    rec = config.user.get("elastic_recovery")
    return int(rec.get("attempt", 0)) if isinstance(rec, dict) else 0


def config_attempt_key(config) -> str:
    return attempt_key(config.job_id, attempt_of(config))


def max_shrinks() -> int:
    """Cap on in-place recoveries per submission (shrink + regrow fences
    both count): a pod losing followers faster than recovery converges
    must eventually fail loudly instead of thrashing forever."""
    return int(os.environ.get("HARMONY_ELASTIC_MAX_SHRINKS", "4"))


def regrow_enabled() -> bool:
    """Whether a recovered follower triggers a re-grow fence on running
    shrunk jobs (HARMONY_ELASTIC_REGROW=0 leaves them degraded)."""
    return os.environ.get("HARMONY_ELASTIC_REGROW", "1").lower() not in (
        "0", "false", "off", "")


def cache_enabled() -> bool:
    """Whether elastic jobs retain a host-side copy of this process's
    blocks at each chain checkpoint (the recovery cache that makes
    partial restore O(lost bytes)). Costs one host copy of the owned
    shard per elastic job; HARMONY_ELASTIC_CACHE=0 trades restore I/O
    for that memory back."""
    return os.environ.get("HARMONY_ELASTIC_CACHE", "1").lower() not in (
        "0", "false", "off", "")
