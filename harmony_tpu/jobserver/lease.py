"""Leader election by lease — file-based, fenced by monotonic epochs.

The control-plane replicas (HARMONY_HA_REPLICAS) elect a leader by
contending on ONE lease file under the shared HA directory
(HARMONY_HA_LOG_DIR — a shared mount in the GKE control plane,
``deploy/gke/controlplane.yaml``; a tmpdir in tests). The protocol is
the classic expiring-lease shape:

  * ``try_acquire``: under an exclusive file lock, read the current
    lease; if it is held by a LIVE peer (now < expires) the attempt
    fails; otherwise write a fresh lease with ``epoch = old + 1`` —
    the monotonic **leader epoch** that fences a deposed leader's late
    writes everywhere downstream (the durable log, RUN_JOB/PLAN
    messages, replication).
  * ``renew``: the holder re-writes ``expires`` every
    ``lease_s / 3`` seconds from a daemon thread. A renewal that finds
    the lease held by someone else (or a higher epoch) means THIS
    process was deposed: ``on_lost`` fires and the manager goes
    invalid — the server stops accepting writes (NOT_LEADER) rather
    than split-braining.
  * ``is_valid``: purely local — true while the last successful
    acquire/renew is younger than the lease duration. A leader that
    cannot reach the lease file long enough for its lease to expire
    must consider ITSELF deposed even before observing a successor
    (the standby may already hold a fresh lease).

Chaos surface: the ``jobserver.lease_renew`` fault site sits on every
renewal — a ``skip`` rule models a wedged leader whose lease silently
expires (the takeover trigger the acceptance test drives).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from harmony_tpu.jobserver.joblog import server_log
from harmony_tpu.utils.durability import fsync_dir

#: operational knobs (docs/DEPLOY.md §7)
ENV_LOG_DIR = "HARMONY_HA_LOG_DIR"
ENV_LEASE_S = "HARMONY_HA_LEASE_S"
ENV_REPLICAS = "HARMONY_HA_REPLICAS"

LEASE_FILENAME = "leader.lease"


def ha_log_dir() -> Optional[str]:
    """The HA state directory, or None when HA is off."""
    return os.environ.get(ENV_LOG_DIR) or None


def lease_seconds() -> float:
    try:
        return float(os.environ.get(ENV_LEASE_S, "10"))
    except ValueError:
        return 10.0


def replica_peers() -> "list[str]":
    """HARMONY_HA_REPLICAS: comma-separated standby log-receiver
    endpoints (``host:port``) the leader streams the durable log to.
    Empty when the deployment replicates through the shared
    HARMONY_HA_LOG_DIR volume instead."""
    raw = os.environ.get(ENV_REPLICAS, "")
    return [p.strip() for p in raw.split(",") if p.strip()]


def read_lease(log_dir: str) -> Optional[Dict[str, Any]]:
    """Read the current lease file (None when absent/unparseable) —
    the shared helper behind every leader-hint lookup (standby
    NOT_LEADER replies, a deposed server's redirect)."""
    from harmony_tpu import faults

    if faults.armed():
        # stale read: "skip" models a crashed-before-dir-fsync store
        # where the file's directory entry never became visible; EIO
        # raise rules land in the same except arm a sick disk would
        try:
            if faults.site("disk.read", kind="lease") == "skip":
                return None
        except OSError:
            return None
    try:
        with open(os.path.join(log_dir, LEASE_FILENAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def leader_hint(log_dir: str, own_holder_id: Optional[str] = None
                ) -> Optional[str]:
    """The LIVE leader's advertised submit address from the lease file,
    or None (expired, missing, or held by ``own_holder_id`` itself)."""
    cur = read_lease(log_dir)
    if not cur or time.time() >= float(cur.get("expires", 0.0)):
        return None
    if own_holder_id is not None and cur.get("holder") == own_holder_id:
        return None
    return cur.get("addr")


class LeaseManager:
    """One replica's handle on the shared leader lease (module doc)."""

    def __init__(self, log_dir: str, holder_id: str,
                 lease_s: Optional[float] = None,
                 on_lost: Optional[Callable[[], None]] = None,
                 addr: Optional[str] = None) -> None:
        self.path = os.path.join(log_dir, LEASE_FILENAME)
        self.holder_id = holder_id
        #: submit endpoint this holder advertises in the lease file —
        #: the redirect target standbys hand out in NOT_LEADER replies
        self.addr = addr
        self.lease_s = float(lease_s if lease_s is not None
                             else lease_seconds())
        self._on_lost = on_lost
        #: the lease read by the LAST successful acquire, BEFORE this
        #: holder overwrote it (who the takeover deposed/succeeded)
        self.previous: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self.epoch = 0
        self._held = False
        #: monotonic stamp of the last SUCCESSFUL acquire/renew
        self._renewed_mono = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.renewals = 0
        self.renew_failures = 0
        os.makedirs(log_dir, exist_ok=True)

    # -- shared-file plumbing -------------------------------------------

    def _locked(self, fn):
        """Run ``fn()`` under the cross-process lease lock (flock on a
        sibling .lock file — same idiom as FaultPlan's shared state)."""
        import fcntl

        with open(self.path + ".lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                return fn()
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)

    def _read(self) -> Optional[Dict[str, Any]]:
        return read_lease(os.path.dirname(self.path))

    def _write(self, lease: Dict[str, Any]) -> None:
        from harmony_tpu import faults

        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            if faults.armed():
                # disk fault class at the lease store: ENOSPC/EIO raise
                # (try_acquire treats the store as unreachable), "delay"
                # is a slow shared mount, "skip" drops the fsync
                act = faults.site("disk.write", kind="lease",
                                  holder=self.holder_id)
            else:
                act = None
            json.dump(lease, f)
            f.flush()
            if act != "skip":
                os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- the protocol ----------------------------------------------------

    def try_acquire(self) -> bool:
        """One election attempt; True iff this replica now holds the
        lease (epoch bumped when taken over from another holder)."""

        def attempt() -> bool:
            cur = self._read()
            now = time.time()
            if (cur and cur.get("holder") != self.holder_id
                    and now < float(cur.get("expires", 0.0))):
                return False  # a live peer holds it
            prev_epoch = int(cur.get("epoch", 0)) if cur else 0
            same = bool(cur) and cur.get("holder") == self.holder_id
            epoch = prev_epoch if same else prev_epoch + 1
            self._write({"holder": self.holder_id, "epoch": epoch,
                         "addr": self.addr,
                         "expires": now + self.lease_s, "acquired": now})
            if cur is None:
                # first-ever acquire CREATED the lease file: the bytes
                # are fsync'd by _write, but the directory entry is not
                # durable until the parent dir is synced — without this
                # a host crash can resurrect an empty HA dir and epoch 1
                # gets minted twice (the same rename/create contract the
                # halog's append-only stream gets for free)
                fsync_dir(self.path)
            if not same:
                self.previous = cur
            with self._lock:
                self.epoch = epoch
                self._held = True
                self._renewed_mono = time.monotonic()
            return True

        try:
            return bool(self._locked(attempt))
        except OSError:
            # the lease store is sick (ENOSPC/EIO/unreachable mount):
            # this attempt simply fails — wait_acquire keeps polling and
            # the election resumes when the store heals
            return False

    def wait_acquire(self, timeout: Optional[float] = None,
                     poll: Optional[float] = None) -> bool:
        """Block until the lease is acquired (or ``timeout``). Polls at
        a fraction of the lease so a takeover lands WITHIN one lease
        window of the old leader's death."""
        poll = poll if poll is not None else max(0.05, self.lease_s / 5.0)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            if self._stop.wait(poll):
                return False

    def renew(self) -> bool:
        """One renewal; False (and ``on_lost``) when deposed."""
        from harmony_tpu import faults

        if faults.armed():
            # "skip" = a wedged leader whose beacon stops: the lease
            # silently runs out and a standby takes over (the chaos
            # trigger). The renewal THREAD survives any injected action.
            try:
                if faults.site("jobserver.lease_renew",
                               holder=self.holder_id,
                               epoch=self.epoch) == "skip":
                    return self.is_valid()
            except Exception:
                return self.is_valid()

        with self._lock:
            if not self._held:
                return False  # released/deposed: never re-extend

        def attempt() -> bool:
            cur = self._read()
            if (not cur or cur.get("holder") != self.holder_id
                    or int(cur.get("epoch", 0)) != self.epoch
                    or cur.get("released")):
                # deposed, or release() already handed the lease off —
                # a renewal racing the release must not re-extend it
                return False
            now = time.time()
            self._write(dict(cur, expires=now + self.lease_s, renewed=now))
            with self._lock:
                self._renewed_mono = time.monotonic()
            return True

        try:
            ok = bool(self._locked(attempt))
        except OSError:
            ok = False  # the lease store is unreachable; validity decays
        with self._lock:
            if ok:
                self.renewals += 1
            else:
                self.renew_failures += 1
        if not ok:
            self._handle_lost()
        return ok

    def _handle_lost(self) -> None:
        with self._lock:
            was_held, self._held = self._held, False
        if was_held:
            server_log.warning(
                "lease lost: %s deposed at epoch %d (a successor holds "
                "a fresh lease, or the store is unreachable)",
                self.holder_id, self.epoch)
            if self._on_lost is not None:
                try:
                    self._on_lost()
                except Exception:
                    pass

    def is_valid(self) -> bool:
        """Local validity: held AND renewed within the lease window.
        The no-clock-trust half of fencing — a leader that cannot renew
        treats itself as deposed once its own lease would have run
        out, successor or not."""
        with self._lock:
            return (self._held and
                    time.monotonic() - self._renewed_mono < self.lease_s)

    # -- renewal thread --------------------------------------------------

    def start_renewal(self) -> None:
        period = max(0.05, self.lease_s / 3.0)

        def loop() -> None:
            while not self._stop.wait(period):
                self.renew()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"ha-lease-{self.holder_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def release(self) -> None:
        """Graceful hand-off: clear the expiry so a standby can take
        over immediately instead of waiting out the window. ``_held``
        flips FIRST and the written lease carries ``released`` — both
        halves of the guard against an in-flight renewal re-extending
        what was just handed off."""
        self.stop()
        with self._lock:
            self._held = False

        def attempt() -> None:
            cur = self._read()
            if cur and cur.get("holder") == self.holder_id:
                self._write(dict(cur, expires=0.0, released=True))

        try:
            self._locked(attempt)
        except OSError:
            pass

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            valid = (self._held and
                     time.monotonic() - self._renewed_mono < self.lease_s)
            return {"holder": self.holder_id, "epoch": self.epoch,
                    "held": self._held, "valid": valid,
                    "lease_s": self.lease_s, "renewals": self.renewals,
                    "renew_failures": self.renew_failures}
