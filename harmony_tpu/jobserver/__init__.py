"""JobServer package.

Exports resolve lazily (PEP 562, the ``dolphin``/``runtime`` precedent):
``jobserver.policy``'s :class:`ActionGate` is consumed by the jax-free
input-service layer (``harmony_tpu.inputsvc``), which must not pay — or
depend on — the jax import chain ``jobserver.server`` pulls in. Eager
``from harmony_tpu.jobserver import JobServer`` style imports behave
exactly as before.
"""
from typing import TYPE_CHECKING

_EXPORTS = {
    "JobScheduler": "harmony_tpu.jobserver.scheduler",
    "ShareAllScheduler": "harmony_tpu.jobserver.scheduler",
    "FifoExclusiveScheduler": "harmony_tpu.jobserver.scheduler",
    "JobEntity": "harmony_tpu.jobserver.entity",
    "DolphinJobEntity": "harmony_tpu.jobserver.entity",
    "JobServer": "harmony_tpu.jobserver.server",
    "CommandSender": "harmony_tpu.jobserver.client",
    "submit_job": "harmony_tpu.jobserver.client",
    "shutdown_server": "harmony_tpu.jobserver.client",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


if TYPE_CHECKING:  # pragma: no cover - static analysis only
    from harmony_tpu.jobserver.client import (
        CommandSender,
        shutdown_server,
        submit_job,
    )
    from harmony_tpu.jobserver.entity import DolphinJobEntity, JobEntity
    from harmony_tpu.jobserver.scheduler import (
        FifoExclusiveScheduler,
        JobScheduler,
        ShareAllScheduler,
    )
    from harmony_tpu.jobserver.server import JobServer
