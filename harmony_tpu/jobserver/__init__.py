from harmony_tpu.jobserver.scheduler import FifoExclusiveScheduler, JobScheduler, ShareAllScheduler
from harmony_tpu.jobserver.entity import DolphinJobEntity, JobEntity
from harmony_tpu.jobserver.server import JobServer
from harmony_tpu.jobserver.client import CommandSender, submit_job, shutdown_server

__all__ = [
    "JobScheduler",
    "ShareAllScheduler",
    "FifoExclusiveScheduler",
    "JobEntity",
    "DolphinJobEntity",
    "JobServer",
    "CommandSender",
    "submit_job",
    "shutdown_server",
]
