"""Control-plane overload detection, admission control, and degradation.

The jobserver is designed to front thousands of tenant jobs, but until
this module the control plane *fell over* rather than degraded: the TCP
command endpoint spawned one unbounded thread per connection, and the
scrape/diagnose/plan loops were full O(tenants) walks that silently
missed their cycle deadlines. This module is the robustness layer the
command plane and the telemetry loops consult:

* **Admission control** — :meth:`OverloadMonitor.admit_submit` answers
  the command plane's "may this SUBMIT enter?" question from queue
  depth + in-flight dispatches. A rejected submission gets a structured
  ``BUSY {retry_after_ms}`` reply (client.py backs off with jitter and
  retries the SAME leader — a busy leader is still the leader); an
  accepted one is either durably in the joblog or was never
  acknowledged, so accepted-then-shed is impossible.
* **Overload detector + ladder** — :meth:`note_queue` /
  :meth:`note_cycle` watch command-queue lag and scrape/diagnose/plan
  cycle overrun; sustained pressure steps the control plane DOWN a
  declared ladder (``normal -> degraded -> shedding``): the scraper
  samples a rotating target subset, doctor/policy evaluate only the
  tenants with fresh samples, and the dashboard tee rate-limits
  harder. Every shed action is counted (``harmony_overload_*``
  instruments) and every transition lands as a structured
  ``kind="overload"`` joblog event under ``__control__`` — the
  ``control_overload`` doctor rule's raw material.
* **Hysteretic recovery** — stepping back UP reuses the existing
  :class:`~harmony_tpu.jobserver.policy.ActionGate`: calm must persist
  ``confirm`` consecutive evaluations and clear the cooldown before the
  ladder re-arms one rung, so a bursty storm cannot flap the plane
  between fidelity levels.

Per "TensorFlow: A system for large-scale machine learning" and
"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md): scale wins come from bounded, overlap-friendly control
structures — a control plane that sheds load predictably instead of
wedging.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from harmony_tpu.jobserver.policy import ActionGate

#: master switch: 0 disables admission control AND the ladder (the
#: benchmark's protection-OFF arm; never disable in production)
ENV_OVERLOAD = "HARMONY_OVERLOAD"
#: fixed command-worker pool size (replaces thread-per-connection)
ENV_WORKERS = "HARMONY_CMD_WORKERS"
#: bounded accept-queue capacity; a full queue sheds at accept
ENV_QUEUE = "HARMONY_CMD_QUEUE"
#: per-command wall-clock deadline (read + handle), milliseconds
ENV_DEADLINE = "HARMONY_CMD_DEADLINE_MS"
#: queue-fill fraction at or above which SUBMIT sheds and the ladder
#: steps down
ENV_HIGH = "HARMONY_OVERLOAD_HIGH"
#: queue-fill fraction below which recovery may step the ladder up
ENV_LOW = "HARMONY_OVERLOAD_LOW"
#: in-flight dispatch count at or above which SUBMIT sheds
ENV_INFLIGHT = "HARMONY_OVERLOAD_INFLIGHT"
#: scrape targets / tenants evaluated per cycle in degraded mode (the
#: rotating subset size)
ENV_SUBSET = "HARMONY_OVERLOAD_SUBSET"

#: the declared degradation ladder, best fidelity first — level is an
#: index into this tuple
LADDER = ("normal", "degraded", "shedding")


def overload_enabled() -> bool:
    """``HARMONY_OVERLOAD`` (default on): 0 disables admission control
    and the degradation ladder — the chaos bench's OFF arm."""
    return os.environ.get(ENV_OVERLOAD, "").strip().lower() not in (
        "0", "off", "false")


def cmd_workers() -> int:
    """``HARMONY_CMD_WORKERS`` (default 8): fixed command-worker pool
    size — the whole command plane's thread budget."""
    try:
        return max(1, int(os.environ.get(ENV_WORKERS, "") or 8))
    except ValueError:
        return 8


def cmd_queue_cap() -> int:
    """``HARMONY_CMD_QUEUE`` (default 64): bounded accept-queue
    capacity; connections past it are answered BUSY at accept."""
    try:
        return max(1, int(os.environ.get(ENV_QUEUE, "") or 64))
    except ValueError:
        return 64


def cmd_deadline_sec() -> float:
    """``HARMONY_CMD_DEADLINE_MS`` (default 10000): per-command
    wall-clock budget in milliseconds, returned in seconds — caps the
    read phase (slow-loris eviction) and bounds a WAIT's future poll."""
    try:
        ms = float(os.environ.get(ENV_DEADLINE, "") or 10000.0)
    except ValueError:
        ms = 10000.0
    return max(0.1, ms / 1000.0)


def overload_high() -> float:
    """``HARMONY_OVERLOAD_HIGH`` (default 0.75): queue-fill fraction at
    or above which SUBMIT sheds and the ladder steps down."""
    try:
        return min(1.0, max(0.05,
                            float(os.environ.get(ENV_HIGH, "") or 0.75)))
    except ValueError:
        return 0.75


def overload_low() -> float:
    """``HARMONY_OVERLOAD_LOW`` (default 0.25): queue-fill fraction
    below which calm counts toward stepping the ladder back up."""
    try:
        return max(0.0, float(os.environ.get(ENV_LOW, "") or 0.25))
    except ValueError:
        return 0.25


def overload_inflight() -> int:
    """``HARMONY_OVERLOAD_INFLIGHT`` (default 256): running-dispatch
    count at or above which SUBMIT sheds — the registry and executor
    pool stay bounded even when the queue itself is drained fast."""
    try:
        return max(1, int(os.environ.get(ENV_INFLIGHT, "") or 256))
    except ValueError:
        return 256


def overload_subset() -> int:
    """``HARMONY_OVERLOAD_SUBSET`` (default 8): rotating-subset size —
    scrape targets per cycle and tenants per doctor/policy evaluation
    while degraded."""
    try:
        return max(1, int(os.environ.get(ENV_SUBSET, "") or 8))
    except ValueError:
        return 8


def _registry():
    from harmony_tpu.metrics.registry import get_registry

    return get_registry()


class OverloadMonitor:
    """The jobserver's overload detector + degradation ladder (module
    docstring). All inputs arrive via ``note_*``; :meth:`step` moves at
    most one ladder rung per call — down immediately under pressure, up
    only through the ActionGate's confirm-streak + cooldown hysteresis.
    Every method takes ``now=`` so tests drive time themselves."""

    #: consecutive cycle overruns of one kind before they count as
    #: pressure (a single slow GC pause is noise, a trend is load)
    OVERRUN_CONFIRM = 2

    def __init__(self, gate: Optional[ActionGate] = None,
                 enabled: Optional[bool] = None) -> None:
        self._lock = threading.Lock()
        self._enabled = overload_enabled() if enabled is None else enabled
        self._level = 0
        # upward recovery shares the policy engine's rate-limit idiom:
        # an ActionGate streak of calm windows + a cooldown per rung
        self.gate = gate or ActionGate(cooldown_sec=10.0, confirm=3,
                                       stale_after=600.0)
        self._fill = 0.0          # newest queue depth / capacity
        self._lag_sec = 0.0       # newest dequeue wait
        self._deadline = cmd_deadline_sec()
        self._overruns: Dict[str, int] = {}  # kind -> consecutive
        self._sheds: Dict[str, int] = {}
        self._rotor: Dict[str, int] = {}     # plan -> rotation cursor
        self._transitions: "deque[Dict[str, Any]]" = deque(maxlen=16)
        self._last_reason = ""

    # -- signal intake ---------------------------------------------------

    def note_queue(self, depth: int, cap: int,
                   lag_sec: Optional[float] = None) -> None:
        """Command-plane sample: accept-queue depth/capacity and (from
        the worker side) how long the dequeued connection waited."""
        with self._lock:
            self._fill = depth / float(max(1, cap))
            if lag_sec is not None:
                self._lag_sec = float(lag_sec)

    def note_cycle(self, kind: str, elapsed_sec: float,
                   budget_sec: float) -> None:
        """Telemetry-loop sample: one scrape/diagnose/plan cycle's wall
        time against its period budget. Consecutive overruns count as
        pressure; one clean cycle clears the streak."""
        with self._lock:
            if elapsed_sec > max(1e-6, budget_sec):
                self._overruns[kind] = self._overruns.get(kind, 0) + 1
            else:
                self._overruns.pop(kind, None)

    # -- pressure + ladder -----------------------------------------------

    def _pressure_reason(self) -> Optional[str]:
        """The active pressure signal, or None when calm (lock held)."""
        if self._fill >= overload_high():
            return f"queue_fill={self._fill:.2f}"
        if self._lag_sec >= 0.5 * self._deadline:
            return f"queue_lag={self._lag_sec * 1000:.0f}ms"
        hot = [k for k, n in self._overruns.items()
               if n >= self.OVERRUN_CONFIRM]
        if hot:
            return "cycle_overrun=" + ",".join(sorted(hot))
        return None

    def step(self, now: Optional[float] = None) -> int:
        """Advance the ladder at most one rung: down immediately under
        pressure, up only after the gate's hysteresis clears. Returns
        the (possibly unchanged) level."""
        if not self._enabled:
            return 0
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            reason = self._pressure_reason()
            level = self._level
            calm = reason is None and self._fill <= overload_low()
        if reason is not None and level < len(LADDER) - 1:
            # descending is immediate — shedding late is wedging
            self.gate.observe("control-plane", "overload_step_up",
                              wanted=False, signal="overload", now=now)
            return self._transition(level + 1, reason, now)
        if level > 0 and calm:
            ready = self.gate.observe("control-plane", "overload_step_up",
                                      wanted=True, signal="overload",
                                      now=now)
            if ready:
                self.gate.fired("control-plane", "overload_step_up",
                                signal="overload", now=now)
                return self._transition(level - 1, "recovered", now)
        elif not calm:
            # pressure gone but fill still above LOW: reset the calm
            # streak — hysteresis means CONSECUTIVE calm windows
            self.gate.observe("control-plane", "overload_step_up",
                              wanted=False, signal="overload", now=now)
        return self._level

    def _transition(self, new_level: int, reason: str, now: float) -> int:
        from harmony_tpu import faults

        with self._lock:
            old, self._level = self._level, new_level
            self._last_reason = reason
            ev = {"from": LADDER[old], "to": LADDER[new_level],
                  "reason": reason, "ts": time.time()}
            self._transitions.append(ev)
        direction = "down" if new_level > old else "up"
        try:
            _registry().counter(
                "harmony_overload_transitions_total",
                "Degradation-ladder transitions by direction "
                "(down = fidelity shed, up = recovered)",
                ("direction",)).labels(direction=direction).inc()
        except Exception:
            pass  # instruments must never fail the control plane
        if faults.armed():
            # chaos hook: a raise here models the detector itself
            # wedging mid-transition — the ladder must stay consistent
            try:
                faults.site("server.overload", direction=direction,
                            level=LADDER[new_level])
            except Exception:
                pass
        try:
            from harmony_tpu.jobserver.joblog import record_event

            record_event("__control__", "overload",
                         ladder=LADDER[new_level], level=new_level,
                         direction=direction, reason=reason,
                         sheds=dict(self._sheds))
        except Exception:
            pass
        return new_level

    # -- admission -------------------------------------------------------

    def admit_submit(self, queue_depth: int, queue_cap: int,
                     inflight: int) -> Optional[int]:
        """Admission decision for ONE SUBMIT: None admits; an int is the
        ``retry_after_ms`` hint of a structured BUSY rejection. Decided
        BEFORE anything durable happens, so a rejected submission left
        no trace and an admitted one cannot be shed later.

        Admission tracks the LIVE queue, not just the ladder: at the
        shedding rung a SUBMIT is still admitted once the queue has
        actually drained to the low-water mark. The ladder's hysteretic
        recovery governs telemetry fidelity; gating admission on it too
        would starve well-behaved backed-off clients for a full
        recovery cycle after every burst (their retries land exactly in
        the drained windows this clause admits)."""
        if not self._enabled:
            return None
        fill = queue_depth / float(max(1, queue_cap))
        with self._lock:
            level = self._level
        if (fill < overload_high() and inflight < overload_inflight()
                and (level < len(LADDER) - 1 or fill <= overload_low())):
            return None
        self.count_shed("busy_reject")
        return self.retry_after_ms(fill=fill, level=level)

    def retry_after_ms(self, fill: Optional[float] = None,
                       level: Optional[int] = None) -> int:
        """Backoff hint scaled by how overloaded we are — deeper ladder
        levels and fuller queues push retries further out (the client
        adds jitter so a storm's retries do not re-arrive in phase)."""
        with self._lock:
            fill = self._fill if fill is None else fill
            level = self._level if level is None else level
        ms = 200.0 * (1 + level) * max(1.0, fill / overload_high())
        return int(min(5000.0, max(100.0, ms)))

    # -- degraded-mode plans ---------------------------------------------

    def degraded(self) -> bool:
        return self._level >= 1

    def shedding(self) -> bool:
        return self._level >= len(LADDER) - 1

    def plan_subset(self, keys: Sequence[str], plan: str,
                    keep: Sequence[str] = ()) -> List[str]:
        """Rotating work subset for one degraded loop (``plan`` names
        the rotor: "scrape", "tenants", ...). Level 0 returns every
        key; degraded levels return ``keep`` plus the next
        ``HARMONY_OVERLOAD_SUBSET``-sized slice, advancing the cursor
        so successive cycles cover the full set. Skips are counted."""
        keys = list(keys)
        if not self.degraded() or not keys:
            return keys
        rest = sorted(k for k in keys if k not in keep)
        k = overload_subset()
        if len(rest) <= k:
            return list(keep) + rest
        with self._lock:
            idx = self._rotor.get(plan, 0) % len(rest)
            self._rotor[plan] = (idx + k) % len(rest)
        picked = [rest[(idx + i) % len(rest)] for i in range(k)]
        self.count_shed(f"{plan}_skip", n=len(rest) - k)
        return list(keep) + picked

    def dashboard_factor(self) -> float:
        """Multiplier on the dashboard tee's rate-limit period: 1x at
        normal fidelity, harder the further down the ladder."""
        return float(4 ** self._level)

    # -- accounting ------------------------------------------------------

    def count_shed(self, action: str, n: int = 1) -> None:
        """One counted shed decision (busy_reject, accept_shed,
        scrape_skip, tenants_skip, policy_skip, dashboard_skip,
        slowloris_evict, deadline_evict)."""
        with self._lock:
            self._sheds[action] = self._sheds.get(action, 0) + n
        try:
            _registry().counter(
                "harmony_overload_shed_total",
                "Control-plane shed decisions by action "
                "(busy_reject, accept_shed, *_skip, *_evict)",
                ("action",)).labels(action=action).inc(n)
        except Exception:
            pass  # instruments must never fail the control plane

    def status(self) -> Dict[str, Any]:
        """The STATUS ``overload`` payload / ``obs top`` header."""
        with self._lock:
            return {
                "enabled": self._enabled,
                "level": self._level,
                "ladder": LADDER[self._level],
                "reason": self._last_reason,
                "queue_fill": round(self._fill, 4),
                "queue_lag_ms": round(self._lag_sec * 1000.0, 1),
                "cycle_overruns": dict(self._overruns),
                "sheds": dict(self._sheds),
                "transitions": list(self._transitions),
                "gate": self.gate.stats(),
            }
