"""Warm-standby JobServer failover — the control-plane HA capstone.

Composition of the two primitives this package already grew:

  * :mod:`harmony_tpu.jobserver.halog` — the durable, replicated,
    CRC-framed log of control-plane state transitions;
  * :mod:`harmony_tpu.jobserver.lease` — file-lease leader election
    with fenced (monotonic) leader epochs.

A control-plane replica runs ONE :class:`HAController`:

  * **standby phase** — a minimal TCP endpoint answers on the submit
    port immediately (STATUS with ``role=standby``; everything else
    gets a ``NOT_LEADER`` reply carrying the current leader's
    advertised address, which the failover client follows), and — in
    peer-replication mode — a :class:`~halog.LogReceiver` applies the
    leader's stream to the local log copy. The replica contends on the
    lease at a fraction of the lease period.
  * **takeover** — the moment the lease lands (the old leader died or
    stopped renewing): replay the log (fenced — a deposed leader's
    late writes are rejected), build the real JobServer through the
    caller's factory, wire the durable log + lease into it
    (``JobServer.enable_ha``), RE-ARM every in-flight submission
    (accepted-but-never-completed in the log) from its committed
    checkpoint chain — elastic jobs continue their attempt sequence
    (``elastic_recovery`` attempt N+1, so stale reports from the old
    leader's attempt can never be misattributed), chained jobs resume
    via ``resume_from_chain``, chainless ones re-run from scratch —
    and start serving the SAME submit port the standby endpoint just
    vacated. Live pod followers re-HELLO on leader change
    (``PodFollower`` reconnects on socket loss), keeping their pids,
    executors and running attempts; trainers ride the existing
    degrade patterns (inputsvc fallback, elastic fences) during the
    takeover window.

One structured ``kind="leader_takeover"`` joblog event records every
takeover (old/new leader, replay ms, re-armed jobs, re-adopted pods);
it rides STATUS, the durable log itself, and the ``leader_flap``
doctor rule.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from harmony_tpu.jobserver import joblog
from harmony_tpu.jobserver.halog import (
    LOG_FILENAME,
    DurableJobLog,
    LogReceiver,
    LogReplicator,
    ReplayState,
)
from harmony_tpu.jobserver.joblog import server_log
from harmony_tpu.jobserver.lease import LeaseManager, replica_peers

#: the pseudo-job id HA-level structured events are recorded under
HA_JOB = "__ha__"


def ha_enabled() -> bool:
    from harmony_tpu.jobserver.lease import ha_log_dir

    return ha_log_dir() is not None


class StandbyEndpoint:
    """Minimal TCP responder a standby runs on the submit port: STATUS
    works (operators can see the replica exists and who leads);
    anything mutating gets ``NOT_LEADER`` plus the leader's advertised
    address so the failover client can redirect instead of guessing."""

    def __init__(self, port: int, info_fn: Callable[[], Dict[str, Any]],
                 leader_hint_fn: Callable[[], Optional[str]],
                 host: str = "127.0.0.1") -> None:
        self._port = port
        self._host = host
        self._info_fn = info_fn
        self._leader_hint_fn = leader_hint_fn
        self._sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._port))
        sock.listen(16)
        sock.settimeout(0.5)
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ha-standby-tcp")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        # a thread blocked inside accept() keeps the PORT bound until it
        # returns — and the takeover rebinds this exact port for the
        # real server, so the vacate must be complete, not just begun
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while True:
            sock = self._sock
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),  # lint: allow(bounded-resource) standby redirect stub: one-line NOT_LEADER reply under a 10s timeout, thread lifetime tracks instantaneous connect rate, not tenant count
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            try:
                conn.settimeout(10.0)
                data = b""
                while not data.endswith(b"\n"):
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    data += chunk
                try:
                    cmd = json.loads(data.decode()).get("command")
                except ValueError:
                    cmd = None
                if cmd == "STATUS":
                    reply: Dict[str, Any] = {
                        "ok": True, "state": "STANDBY", "running": [],
                        "ha": self._info_fn(),
                    }
                else:
                    reply = {
                        "ok": False, "not_leader": True,
                        "error": "NOT_LEADER: this replica is a warm "
                                 "standby",
                        "leader": self._leader_hint_fn(),
                    }
                conn.sendall((json.dumps(reply) + "\n").encode())
            except OSError:
                pass


class HAController:
    """One control-plane replica: standby until the lease lands, then
    take over (module docstring). ``server_factory()`` returns an
    UNSTARTED JobServer/PodJobServer; ``on_leader(server)`` (optional)
    runs after ``server.start()`` and before the submit port opens —
    the pod hook point (``serve_pod``)."""

    def __init__(
        self,
        server_factory: Callable[[], Any],
        log_dir: str,
        replica_id: str,
        submit_port: int = 0,
        advertise_addr: Optional[str] = None,
        recv_port: Optional[int] = None,
        peers: Optional[List[str]] = None,
        lease_s: Optional[float] = None,
        on_leader: Optional[Callable[[Any], None]] = None,
        bind_host: str = "127.0.0.1",
    ) -> None:
        self._factory = server_factory
        self.log_dir = log_dir
        self.replica_id = replica_id
        self.submit_port = submit_port
        self.advertise_addr = advertise_addr
        self._recv_port = recv_port
        self.peers = peers if peers is not None else replica_peers()
        self._lease_s = lease_s
        self._on_leader = on_leader
        #: interface the standby endpoint AND the post-takeover server
        #: bind — loopback by default (the single-machine contract);
        #: cross-host deployments pass the advertised interface
        #: (cli --ha-bind, deploy/gke/controlplane.yaml)
        self.bind_host = bind_host
        self.log_path = os.path.join(log_dir, LOG_FILENAME)
        self.lease: Optional[LeaseManager] = None
        self.server: Optional[Any] = None
        self.receiver: Optional[LogReceiver] = None
        self.standby: Optional[StandbyEndpoint] = None
        self.port: Optional[int] = None
        self.replay_ms: Optional[float] = None
        self.rearmed: List[str] = []
        self._stop = threading.Event()
        self._leader_ready = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: guards port/receiver/server/replay bookkeeping — start()
        #: runs on the caller's thread, _takeover on the controller's
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "HAController":
        """Begin the standby→leader state machine on its own thread;
        the standby endpoint answers the submit port before this
        returns."""
        standby = StandbyEndpoint(self.submit_port, self._standby_info,
                                  self._leader_hint, host=self.bind_host)
        port = standby.start()
        with self._lock:
            self.standby = standby
            self.port = port
        if self._recv_port is not None:
            # peer-replication mode: this replica's LOCAL log copy is
            # fed by the leader's stream. (Shared-volume mode must NOT
            # open the shared file while the leader appends — it is
            # opened once, at takeover.)
            receiver = LogReceiver(DurableJobLog(self.log_path),
                                   port=self._recv_port)
            receiver.start()
            with self._lock:
                self.receiver = receiver
        with self._lock:
            self.lease = LeaseManager(
                self.log_dir, self.replica_id, lease_s=self._lease_s,
                addr=self.advertise_addr or f"127.0.0.1:{self.port}",
                on_lost=self._on_deposed,
            )
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ha-{self.replica_id}")
        self._thread.start()
        return self

    def wait_leader(self, timeout: Optional[float] = None) -> bool:
        """Block until THIS replica has completed a takeover."""
        return self._leader_ready.wait(timeout)

    def stop(self, shutdown_timeout: float = 60.0) -> None:
        self._stop.set()
        if self.lease is not None:
            self.lease.stop()
        if self.standby is not None:
            self.standby.stop()
        with self._lock:
            receiver, self.receiver = self.receiver, None
            server, self.server = self.server, None
        if receiver is not None:
            receiver.stop()
            receiver.log.close()
        if server is not None:
            try:
                server.shutdown(timeout=shutdown_timeout)
            except Exception:
                pass
        if self.lease is not None:
            self.lease.release()

    # -- standby ---------------------------------------------------------

    def _standby_info(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "role": "standby",
            "replica": self.replica_id,
            "leader": self._leader_hint(),
            "log": (self.receiver.stats()
                    if self.receiver is not None else None),
        }

    def _leader_hint(self) -> Optional[str]:
        """The live leader's advertised submit address, from the lease
        file — the redirect target NOT_LEADER replies carry."""
        from harmony_tpu.jobserver.lease import leader_hint, read_lease

        cur = read_lease(self.log_dir)
        if cur and cur.get("holder") == self.replica_id:
            return self.advertise_addr
        return leader_hint(self.log_dir)

    def _on_deposed(self) -> None:
        """This replica lost a lease it held. The server (if any) is
        already fenced — its lease went invalid, so submits answer
        NOT_LEADER and durable appends are refused — but say so loudly;
        split-brain avoidance depends on the operator seeing this."""
        server_log.error(
            "HA replica %s DEPOSED at epoch %s: a successor holds the "
            "lease; this server now answers NOT_LEADER",
            self.replica_id,
            self.lease.epoch if self.lease is not None else "?")

    # -- takeover --------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.lease.wait_acquire():
                return  # stopped while standing by
            if self._stop.is_set():
                return
            # renewal starts the moment the lease lands — the takeover
            # itself (server factory = jax runtime init, log replay)
            # can easily outlast one lease window, and an unrenewed
            # lease mid-takeover would let a peer elect itself and run
            # the same re-armed submissions concurrently
            self.lease.start_renewal()
            try:
                self._takeover()
                return
            except Exception as e:  # noqa: BLE001 - a failed takeover
                # must be visible, the lease released so a peer can
                # try, and THIS replica must return to standby — an
                # inert process that neither answers its port nor
                # contends would silently shrink the replica set
                server_log.error("HA takeover by %s FAILED: %s: %s",
                                 self.replica_id, type(e).__name__, e)
                self.lease.release()
                if self._stop.wait(max(0.2, self.lease.lease_s / 2.0)):
                    return
                self._restandby()

    def _restandby(self) -> None:
        """Rebuild the standby phase after a failed takeover: re-open
        the standby endpoint (the takeover stopped it) on the same
        port, and a FRESH lease manager (release() stopped the old
        one's event machinery)."""
        standby = StandbyEndpoint(
            self.port or self.submit_port, self._standby_info,
            self._leader_hint, host=self.bind_host)
        with self._lock:
            self.standby = standby
        try:
            port = standby.start()
            with self._lock:
                self.port = port
        except OSError as e:
            # the port may be momentarily unreleasable after a failed
            # serve_tcp bind; standing by without the endpoint is still
            # better than exiting — the replica keeps contending
            server_log.warning(
                "HA %s: standby endpoint re-bind failed (%s); standing "
                "by without it", self.replica_id, e)
        with self._lock:
            self.lease = LeaseManager(
                self.log_dir, self.replica_id, lease_s=self._lease_s,
                addr=self.advertise_addr or f"127.0.0.1:{self.port}",
                on_lost=self._on_deposed,
            )

    def _takeover(self) -> None:
        from harmony_tpu import faults

        t0 = time.perf_counter()
        prev = self.lease.previous or {}
        if faults.armed():
            faults.site("jobserver.takeover", replica=self.replica_id,
                        epoch=self.lease.epoch)
        # the standby endpoint vacates the submit port for the real
        # server; the receiver's stream is superseded by leadership
        with self._lock:
            receiver, self.receiver = self.receiver, None
        if receiver is not None:
            receiver.stop()
            receiver.log.close()
        self.standby.stop()
        log = DurableJobLog(self.log_path)  # truncates any torn tail
        server = None
        try:
            log.set_epoch(self.lease.epoch)
            state = ReplayState.from_entries(log.entries())
            # the REPLAYED takeover history seeds this process's joblog
            # ring BEFORE enable_ha hooks the durable sink (no
            # re-append): leader_flap and STATUS must see the cluster's
            # takeover history, not just this process's own event —
            # every takeover happens in a different process
            for e in state.takeovers[-8:]:
                joblog.record_event(
                    HA_JOB, "leader_takeover",
                    **{k: v for k, v in e.items()
                       if k not in ("seq", "epoch", "kind", "job")})
            replicator = (LogReplicator(log, self.peers)
                          if self.peers else None)
            server = self._factory()
            server.enable_ha(log, lease=self.lease, replicator=replicator,
                             replica_id=self.replica_id)
            server.start()
            if self._on_leader is not None:
                self._on_leader(server)
            port = server.serve_tcp(self.submit_port or (self.port or 0),
                                    host=self.bind_host)
            if not self.lease.is_valid():
                # the lease lapsed mid-takeover despite renewals (store
                # unreachable): a successor may already lead — abort
                # BEFORE re-arming anything
                raise RuntimeError("lease lapsed during takeover")
            rearmed = self._rearm(server, state)
            self._seed_done(server, state)
            # adopt the predecessor's persisted incidents: mid-flight
            # episodes stay OPEN on this successor, so post-takeover
            # resolution evidence still joins them (resolved ones land
            # in the history ring; nothing is re-appended to the log)
            try:
                server.incidents.adopt(state.incidents)
            except Exception:
                pass  # incident history must never fail a takeover
        except BaseException:
            # a half-complete takeover must not leak a running server,
            # an open log handle, or a registered joblog sink into the
            # re-standby cycle
            if server is not None:
                try:
                    server.shutdown(timeout=15.0)  # _stop_ha closes log
                except Exception:
                    pass
            else:
                log.close()
            raise
        with self._lock:
            self.port = port
            self.rearmed = rearmed
            self.replay_ms = round((time.perf_counter() - t0) * 1000.0, 2)
            self.server = server
        pods = sorted(getattr(server, "_followers", {}) or {})
        ev = joblog.record_event(
            HA_JOB, "leader_takeover",
            old_leader=prev.get("holder"),
            new_leader=self.replica_id,
            epoch=self.lease.epoch,
            replay_ms=self.replay_ms,
            replayed_entries=state.entries_applied,
            rejected_stale=state.rejected_stale,
            rearmed=list(self.rearmed),
            readopted_pods=pods,
        )
        dash = getattr(server, "_dashboard", None)
        if dash is not None:
            # same best-effort recovery-row contract as the pod events:
            # the dashboard's per-job recoveries column shows takeovers
            try:
                dash.post(HA_JOB, "recovery", dict(ev))
            except Exception:
                pass
        server_log.info(
            "HA takeover complete: %s leads at epoch %d (replay %.1f ms, "
            "%d in-flight submission(s) re-armed, port %d)",
            self.replica_id, self.lease.epoch, self.replay_ms,
            len(self.rearmed), self.port)
        self._leader_ready.set()

    def _rearm(self, server: Any, state: ReplayState) -> List[str]:
        """Re-arm every in-flight submission from the replayed log:
        elastic jobs continue their attempt sequence, chained jobs
        resume from the last committed chain entry, chainless ones
        re-run from scratch (nothing of theirs was ever committed)."""
        from harmony_tpu.config.base import ConfigBase

        rearmed: List[str] = []
        for job in state.in_flight():
            try:
                cfg = ConfigBase.from_dict(state.submissions[job])
                has_chain = self._has_chain(server, job)
                if has_chain and cfg.user.get("elastic_shrink"):
                    # continue the SAME submission's attempt sequence:
                    # the attempt key isolates any straggling report
                    # from an attempt the dead leader had in flight
                    cfg.user["elastic_recovery"] = {
                        "attempt": state.attempts.get(job, 0) + 1,
                        "kind": "shrink",
                        "lost_executors": [],
                    }
                elif has_chain:
                    cfg.user["resume_from_chain"] = True
                server.submit(cfg)
                rearmed.append(job)
            except Exception as e:  # noqa: BLE001 - re-arm the rest
                server_log.error(
                    "takeover re-arm of %s failed: %s: %s",
                    job, type(e).__name__, e)
        return rearmed

    @staticmethod
    def _seed_done(server: Any, state: "ReplayState") -> None:
        """Register every COMPLETED submission's terminal outcome from
        the replayed log, so a WAIT on the successor answers done for
        it instead of 'unknown job' until the client's deadline: a
        client following its acknowledged submission across a failover
        gets a definitive reply whether the job finished under the old
        leader or gets re-armed here. The workers' result payload died
        with the old leader's process — only the terminal ok/error
        rides the log — so the seeded result says exactly that."""
        from harmony_tpu.jobserver.server import JobResult

        for job, entry in state.done.items():
            if job not in state.submissions:
                continue
            jr = JobResult()
            if entry.get("ok"):
                jr.future.set_result({
                    "done": True, "ok": True, "replayed": True,
                    "epoch": entry.get("epoch")})
            else:
                jr.future.set_exception(RuntimeError(
                    f"job {job} failed under a previous leader: "
                    f"{entry.get('error')}"))
            with server._lock:
                server._jobs.setdefault(job, jr)

    @staticmethod
    def _has_chain(server: Any, job: str) -> bool:
        root = getattr(server, "_chkp_root", None)
        if not root:
            return False
        try:
            from harmony_tpu.checkpoint.manager import CheckpointManager

            mgr = CheckpointManager.for_job(root, job)
            prefix = f"{job}:"
            return any(c.startswith(prefix)
                       for c in mgr.list_checkpoints())
        except Exception:
            return False
