"""Durable replicated job log — the control plane's source of truth.

PR 3's joblog gave the recovery paths a STRUCTURED event stream, but it
is per-process and in-memory: when the JobServer leader dies, every
submission, attempt, fence and chain pointer dies with it. This module
promotes that stream into an append-only, fsync'd, CRC-framed on-disk
log of control-plane state transitions — submission accepted (config +
``_trace`` included, so a takeover can re-arm the SAME submission),
dispatch, attempt start/end, elastic fence/shrink/re-grow, checkpoint
chain commits, completion — plus the machinery a warm standby needs:

  * :class:`DurableJobLog` — the on-disk log. One record per entry:
    ``u32 length | u32 crc32(payload) | payload`` (little-endian, JSON
    payload). Appends are a single write + flush + fsync, so a
    committed record survives process death; replay tolerates a TORN
    TAIL (a crash mid-append) by truncating at the last whole,
    CRC-valid record — exactly the torn-commit stance the checkpoint
    chain takes (manifest-written-last).
  * :class:`LogReplicator` / :class:`LogReceiver` — leader→standby
    streaming over the PR-5 framed-stream wire (utils/framing.py): the
    receiver opens with its last applied seq, the replicator streams
    the missing suffix from disk (catch-up after any gap) and then
    live entries; reconnects re-run the same handshake, so replication
    is idempotent by seq.
  * :class:`ReplayState` — reconstructs scheduler/arbiter/elastic
    state from the entries: in-flight submissions (accepted minus
    completed), last attempt per job, committed chain pointers, and
    the takeover history. FENCED: entries stamped with a leader epoch
    lower than one already replayed are a deposed leader's late writes
    and are rejected, never applied.

The reference system's long-running JobServer master keeps all of this
in one process (SURVEY.md §0); parameter-service systems make the same
state durable so aggregation survives server churn (arXiv:2204.03211),
and TensorFlow's long-running training leans on durable state +
re-adoption across coordinator restarts (arXiv:1605.08695).
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from harmony_tpu import faults
from harmony_tpu.jobserver.joblog import server_log
from harmony_tpu.utils.framing import read_exact, send_frame_parts, set_nodelay

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
#: sanity bound on one record — a length field past this is torn/garbage
_MAX_RECORD = 16 << 20
#: default on-disk log filename under HARMONY_HA_LOG_DIR
LOG_FILENAME = "job.walog"


class StaleEpochError(RuntimeError):
    """A write stamped with a leader epoch older than one the log has
    already accepted — a deposed leader's late append. Rejecting it is
    the fencing contract: after a takeover, nothing the old leader
    still has in flight can contaminate the new leader's history."""

    def __init__(self, entry_epoch: int, fence_epoch: int) -> None:
        super().__init__(
            f"fenced: entry epoch {entry_epoch} < log epoch {fence_epoch} "
            "(a deposed leader's late write)"
        )
        self.entry_epoch = entry_epoch
        self.fence_epoch = fence_epoch


def encode_record(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def scan_records(path: str) -> Tuple[List[Dict[str, Any]], int, int]:
    """Scan the log file: returns (entries, good_bytes, torn_bytes).
    ``good_bytes`` is the offset of the last whole CRC-valid record's
    end; anything past it (a torn tail from a crash mid-append, or
    trailing corruption) counts in ``torn_bytes`` and is NOT decoded."""
    entries: List[Dict[str, Any]] = []
    good = 0
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            while True:
                head = f.read(_HEADER.size)
                if len(head) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(head)
                if length > _MAX_RECORD:
                    break
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                try:
                    entries.append(json.loads(payload.decode()))
                except (ValueError, UnicodeDecodeError):
                    break  # framed but unparseable: treat as torn
                good = f.tell()
    except FileNotFoundError:
        return [], 0, 0
    return entries, good, max(0, size - good)


class DurableJobLog:
    """Append-only fsync'd control-plane log (module docstring).

    ``fence_epoch`` is the highest leader epoch the log has accepted;
    :meth:`append` rejects lower-epoch writes with
    :class:`StaleEpochError`. Appends tee to registered sinks (the
    replicator) AFTER the record is durable — a standby can never hold
    an entry the leader's disk does not."""

    def __init__(self, path: str, fsync: bool = True) -> None:
        self.path = path
        self._fsync = fsync
        entries, good, torn = scan_records(path)
        if torn:
            server_log.warning(
                "halog: truncating %d torn byte(s) at the tail of %s "
                "(%d whole record(s) kept)", torn, path, len(entries))
            with open(path, "rb+") as f:
                f.truncate(good)
        self.torn_recovered = torn
        self._lock = threading.Lock()
        self._seq = max((int(e.get("seq", 0)) for e in entries), default=0)
        self.fence_epoch = max(
            (int(e.get("epoch", 0)) for e in entries), default=0)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        self._sinks: List[Callable[[Dict[str, Any], bytes], None]] = []
        self.appends = 0
        self.append_bytes = 0
        #: cumulative seconds spent inside durable appends (the
        #: write+flush+fsync cost the bench hook tracks)
        self.append_seconds = 0.0
        # -- group commit (burst batching) --------------------------------
        # ``_lock`` orders writes (seq assignment + file write + pending
        # enqueue); ``_commit_lock`` serializes the flush+fsync+sink
        # stage. Under burst, the committer that holds ``_commit_lock``
        # fsyncs EVERY record written so far in one syscall; the writers
        # it covered find ``_durable_n`` past their token and return
        # without paying their own fsync. At low load (no contention)
        # each append still does exactly one write+flush+fsync — the
        # single-append latency the bench holds unregressed.
        self._commit_lock = threading.Lock()
        self._wrote_n = 0    # monotonic write token (NOT the wire seq —
        self._durable_n = 0  # a receiver mirrors the leader's seqs)
        #: written-but-not-yet-sunk entries, append order == seq order
        self._pending: List[Tuple[int, Dict[str, Any], bytes]] = []
        #: fsync syscalls actually issued — appends/group_commits is the
        #: burst batching factor
        self.group_commits = 0
        # HARMONY_LOG_BATCH_MS: optional coalescing window. A committer
        # that wins ``_commit_lock`` sleeps this long BEFORE the fsync so
        # burst writers pile into one syscall even when their appends are
        # microseconds apart-but-serial. 0 (default) = commit immediately
        # (contention-only batching, the original behavior).
        try:
            self._batch_s = max(
                0.0, float(os.environ.get("HARMONY_LOG_BATCH_MS", "0")
                           or 0.0)) / 1000.0
        except ValueError:
            self._batch_s = 0.0
        self._closed = False

    # -- write side ------------------------------------------------------

    def set_epoch(self, epoch: int) -> None:
        """Raise the fence floor (a freshly elected leader stamps its
        lease epoch here before its first append)."""
        with self._lock:
            if epoch < self.fence_epoch:
                raise StaleEpochError(epoch, self.fence_epoch)
            self.fence_epoch = int(epoch)

    def append(self, kind: str, job_id: Optional[str] = None,
               epoch: Optional[int] = None, seq: Optional[int] = None,
               **fields: Any) -> Dict[str, Any]:
        """Append one durable entry; returns it (with seq/epoch/ts).
        Raises StaleEpochError for a fenced (deposed-leader) write.
        ``seq`` preserves an upstream sequence number (the replication
        receiver passes the LEADER's seq verbatim, so the local copy's
        numbering can never drift from the stream it mirrors); local
        writers leave it None and get the next local seq."""
        from harmony_tpu import faults

        t0 = time.perf_counter()
        with self._lock:
            ep = self.fence_epoch if epoch is None else int(epoch)
            if ep < self.fence_epoch:
                raise StaleEpochError(ep, self.fence_epoch)
            self.fence_epoch = ep
            prev_seq = self._seq
            self._seq = int(seq) if seq is not None \
                else self._seq + 1
            entry = {"seq": self._seq, "epoch": ep, "ts": time.time(),
                     "kind": kind, "job": job_id, **fields}
            # tail-repair bracket: flush so the buffer is empty, note
            # the durable size, and on ANY write failure truncate back
            # to it. Without this a torn append (partial write + EIO)
            # leaves half a record mid-stream and every LATER append
            # lands beyond the tear — scan_records() stops at the first
            # bad header, so acked-and-fsynced entries behind it become
            # unreplayable. (Found by the chaos sweep's
            # halog_torn_write schedule: 3 acked submissions vanished
            # from replay behind one torn record.)
            self._f.flush()
            good_off = os.fstat(self._f.fileno()).st_size
            try:
                if faults.armed():
                    # "raise" here models a failing log disk; "delay" a
                    # slow fsync — both surface like the real fault
                    faults.site("jobserver.log_append", kind=kind,
                                seq=self._seq)
                payload = json.dumps(entry, sort_keys=True,
                                     default=repr).encode()
                rec = encode_record(payload)
                if faults.armed():
                    # disk fault class: ENOSPC/EIO raise here; "corrupt"
                    # is a torn write — a prefix of the record reaches
                    # the platter and the append dies
                    act = faults.site("disk.write", kind="halog",
                                      seq=self._seq)
                    if act == "corrupt":
                        self._f.write(rec[:max(1, len(rec) // 2)])
                        self._f.flush()
                        raise faults.DiskIOError(
                            f"injected torn halog write [seq={self._seq}]")
                self._f.write(rec)
            except Exception:
                self._seq = prev_seq
                try:
                    self._f.flush()
                except OSError:
                    pass  # the partial bytes may not even flush — the
                #         truncate below repairs whatever landed
                try:
                    os.ftruncate(self._f.fileno(), good_off)
                except OSError:
                    pass  # repair failed too: the reopen-time
                #         scan_records() truncation is the backstop
                raise
            self._wrote_n += 1
            token = self._wrote_n
            self._pending.append((token, entry, rec))
            self.appends += 1
            self.append_bytes += len(rec)
        # durability + sink delivery OUTSIDE the write lock: concurrent
        # writers keep appending while one committer fsyncs the batch
        self._commit(token)
        self.append_seconds += time.perf_counter() - t0
        return entry

    def _commit(self, token: int) -> None:
        """Group commit: make every record written up to (at least)
        ``token`` durable, then deliver the covered entries to the
        sinks. ``_commit_lock`` serializes committers, so sink delivery
        stays in seq order — two concurrent appends must enqueue into
        the replicator in seq order, or the receiver's seq-idempotence
        would drop the late-arriving lower seq as a duplicate (a
        silent, permanent hole in the standby's log). A writer whose
        record was covered by an earlier committer's fsync returns
        without a syscall — that is the whole burst win."""
        with self._commit_lock:
            with self._lock:
                if self._durable_n >= token or self._closed:
                    return  # covered (and sunk) by an earlier committer
                batch_s = self._batch_s
            if batch_s > 0.0:
                # coalescing window: let trailing writers land before the
                # one fsync covers them all
                time.sleep(batch_s)
            with self._lock:
                if self._durable_n >= token or self._closed:
                    return  # close() drained the tail while we slept
                self._f.flush()
                top = self._wrote_n
                sinks = list(self._sinks)
            if self._fsync:
                if faults.armed():
                    # slow fsync (delay), EIO (raise), or a lying disk
                    # that never syncs ("skip" — the power-loss hole)
                    if faults.site("disk.fsync", kind="halog") != "skip":
                        os.fsync(self._f.fileno())
                else:
                    os.fsync(self._f.fileno())
            self.group_commits += 1
            with self._lock:
                self._durable_n = top
                batch: List[Tuple[int, Dict[str, Any], bytes]] = []
                while self._pending and self._pending[0][0] <= top:
                    batch.append(self._pending.pop(0))
            # sinks run under the COMMIT lock (not the write lock): the
            # replicator's peer loop reads last_seq (write lock) before
            # its cond and never takes the commit lock — no ABBA
            for _tok, entry, rec in batch:
                for sink in sinks:
                    try:
                        sink(entry, rec)
                    except Exception:  # replication is best-effort per
                        pass           # append; catch-up repairs gaps

    def add_sink(self, fn: Callable[[Dict[str, Any], bytes], None]) -> None:
        with self._lock:
            self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    # -- read side -------------------------------------------------------

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def entries(self, since_seq: int = 0) -> List[Dict[str, Any]]:
        """Whole-file scan (torn tail skipped, never truncated here),
        filtered to seq > ``since_seq`` — the replicator's catch-up
        source and the takeover replay input."""
        with self._lock:
            self._f.flush()
        out, _good, _torn = scan_records(self.path)
        return [e for e in out if int(e.get("seq", 0)) > since_seq]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "last_seq": self._seq,
                "fence_epoch": self.fence_epoch,
                "appends": self.appends,
                "append_bytes": self.append_bytes,
                "append_seconds": round(self.append_seconds, 6),
                # fsync syscalls actually paid: appends/group_commits
                # is the burst batching factor (1.0 at low load)
                "group_commits": self.group_commits,
                "torn_recovered_bytes": self.torn_recovered,
                "sinks": len(self._sinks),
            }

    def close(self) -> None:
        # One final commit so nothing written stays un-fsynced: close
        # may race a burst's covered writers that already returned. The
        # pending tail must ALSO reach the sinks — a stop() landing
        # inside the HARMONY_LOG_BATCH_MS coalescing window used to
        # drop the entries whose sleeping committer never woke to
        # deliver them (the standby then missed the run's last acks).
        with self._commit_lock:
            with self._lock:
                self._closed = True
                sinks = list(self._sinks)
                batch = list(self._pending)
                self._pending.clear()
                if batch:
                    self._durable_n = max(self._durable_n, batch[-1][0])
                try:
                    self._f.flush()
                    if self._fsync:
                        os.fsync(self._f.fileno())
                except (OSError, ValueError):
                    pass  # already closed / torn fd: nothing to save
                try:
                    self._f.close()
                except OSError:
                    pass
            # sink delivery outside the write lock, same discipline as
            # _commit (the replicator sink takes its own cond)
            for _tok, entry, rec in batch:
                for sink in sinks:
                    try:
                        sink(entry, rec)
                    except Exception:
                        pass


# -- replication ------------------------------------------------------------


def _send_record(sock: socket.socket, payload: bytes) -> None:
    send_frame_parts(
        sock, _HEADER.pack(len(payload), zlib.crc32(payload)), [payload],
        role="halog.repl")


def _recv_record(sock: socket.socket) -> Optional[bytes]:
    head = read_exact(sock, _HEADER.size)
    if head is None:
        return None
    length, crc = _HEADER.unpack(bytes(head))
    if length > _MAX_RECORD:
        raise ValueError(f"replication frame length {length} exceeds cap")
    payload = read_exact(sock, length)
    if payload is None:
        return None
    payload = bytes(payload)
    if zlib.crc32(payload) != crc:
        raise ValueError("replication frame CRC mismatch")
    return payload


class LogReplicator:
    """Leader side: stream every durable entry to the standby receivers
    named by ``peers`` (``host:port`` strings — HARMONY_HA_REPLICAS).
    One daemon thread per peer: connect (bounded backoff), read the
    receiver's ``{"last_seq": n}`` hello, send the missing suffix from
    disk, then drain the live queue. Any error drops the connection;
    the reconnect handshake re-runs catch-up, so a gap is repaired, not
    accumulated."""

    def __init__(self, log: DurableJobLog, peers: List[str],
                 connect_timeout: float = 5.0) -> None:
        self.log = log
        self.peers = list(peers)
        self._connect_timeout = connect_timeout
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._queues: Dict[str, List[bytes]] = {p: [] for p in self.peers}
        self._cond = threading.Condition(self._lock)
        self._state: Dict[str, Dict[str, Any]] = {
            p: {"connected": False, "sent_seq": 0, "reconnects": 0,
                "resync": False}
            for p in self.peers
        }
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        self.log.add_sink(self._on_append)
        for peer in self.peers:
            t = threading.Thread(target=self._peer_loop, args=(peer,),
                                 daemon=True, name=f"halog-repl-{peer}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self.log.remove_sink(self._on_append)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    def _on_append(self, entry: Dict[str, Any], rec: bytes) -> None:
        with self._cond:
            for peer, q in self._queues.items():
                q.append(rec)
                # bound leader memory under a slow/dead standby: drop
                # the buffered backlog AND force that peer's connection
                # to resync — a silent mid-stream drop would be a gap
                # the receiver never notices; the reconnect handshake
                # re-reads the missing suffix from disk instead
                if len(q) > 4096:
                    q.clear()
                    self._state[peer]["resync"] = True
            self._cond.notify_all()

    def _peer_loop(self, peer: str) -> None:
        host, _, port = peer.rpartition(":")
        delay = 0.2
        while not self._stop.is_set():
            try:
                from harmony_tpu.faults.partition import fault_connect

                with fault_connect(
                        (host or "127.0.0.1", int(port)), role="halog.repl",
                        timeout=self._connect_timeout) as sock:
                    set_nodelay(sock)
                    sock.settimeout(30.0)
                    hello = _recv_record(sock)
                    if hello is None:
                        raise OSError("receiver closed during hello")
                    last_seq = int(json.loads(hello.decode())
                                   .get("last_seq", 0))
                    with self._cond:
                        self._queues[peer].clear()
                        self._state[peer]["connected"] = True
                        self._state[peer]["resync"] = False
                    # catch-up: everything the receiver is missing,
                    # re-framed from disk (the gap repair)
                    sent = last_seq
                    for e in self.log.entries(since_seq=last_seq):
                        payload = json.dumps(e, sort_keys=True,
                                             default=repr).encode()
                        _send_record(sock, payload)
                        sent = int(e["seq"])
                    with self._cond:
                        self._state[peer]["sent_seq"] = sent
                    delay = 0.2
                    while not self._stop.is_set():
                        with self._cond:
                            while (not self._queues[peer]
                                   and not self._state[peer]["resync"]
                                   and not self._stop.is_set()):
                                self._cond.wait(timeout=1.0)
                            if self._state[peer]["resync"]:
                                # backlog overflowed mid-connection:
                                # reconnect so catch-up repairs the gap
                                raise OSError(
                                    "replication backlog overflow")
                            batch = self._queues[peer][:]
                            self._queues[peer].clear()
                        for rec in batch:
                            if faults.armed():
                                from harmony_tpu.faults.partition import (
                                    frame_dropped)

                                if frame_dropped(sock, role="halog.repl"):
                                    continue
                            sock.sendall(rec)
                        if batch:
                            # read the log's seq BEFORE taking the cond:
                            # append holds log._lock while calling the
                            # sink (which takes this cond) — taking the
                            # locks here in the opposite order would be
                            # a classic ABBA deadlock
                            last = self.log.last_seq
                            with self._cond:
                                self._state[peer]["sent_seq"] = last
            except (OSError, ValueError) as e:
                with self._cond:
                    if self._state[peer]["connected"]:
                        server_log.warning(
                            "halog replicator: peer %s dropped (%s); "
                            "will catch up on reconnect", peer, e)
                    self._state[peer]["connected"] = False
                    self._state[peer]["reconnects"] += 1
                if self._stop.wait(delay):
                    return
                delay = min(delay * 2, 5.0)

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {p: dict(s) for p, s in self._state.items()}


class LogReceiver:
    """Standby side: accept the leader's replication stream and append
    received entries to the LOCAL durable log, so a takeover can replay
    from this replica's own disk. Seq-idempotent (duplicates from a
    catch-up overlap are skipped) and epoch-fenced (entries below the
    local fence epoch are rejected and counted)."""

    def __init__(self, log: DurableJobLog, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.log = log
        self._host = host
        self._port = port
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.port: Optional[int] = None
        self.received = 0
        self.rejected_stale = 0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._port))
        sock.listen(4)
        sock.settimeout(1.0)
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="halog-recv")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._thread is not None:
            # the accept loop keeps the port bound until it returns; a
            # stopped receiver must have fully vacated it (reuse/tests)
            self._thread.join(timeout=3.0)
            self._thread = None

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except (OSError, AttributeError):
                return
            threading.Thread(target=self._serve_conn, args=(conn,),  # lint: allow(bounded-resource) peers are replication leaders (one long-lived conn per epoch), bounded by replica count
                             daemon=True, name="halog-recv-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            try:
                set_nodelay(conn)
                conn.settimeout(60.0)
                _send_record(conn, json.dumps(
                    {"last_seq": self.log.last_seq}).encode())
                while not self._stop.is_set():
                    payload = _recv_record(conn)
                    if payload is None:
                        return
                    entry = json.loads(payload.decode())
                    self._apply(entry)
            except (OSError, ValueError) as e:
                if not self._stop.is_set():
                    server_log.warning(
                        "halog receiver: stream error (%s); awaiting "
                        "reconnect", e)

    def _apply(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            if int(entry.get("seq", 0)) <= self.log.last_seq:
                return  # catch-up overlap: seq-idempotent
            try:
                fields = {k: v for k, v in entry.items()
                          if k not in ("seq", "epoch", "ts", "kind", "job")}
                self.log.append(entry["kind"], job_id=entry.get("job"),
                                epoch=int(entry.get("epoch", 0)),
                                seq=int(entry["seq"]), **fields)
                self.received += 1
            except StaleEpochError:
                self.rejected_stale += 1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"port": self.port, "received": self.received,
                    "rejected_stale": self.rejected_stale,
                    "last_seq": self.log.last_seq}


# -- replay ------------------------------------------------------------------


class ReplayState:
    """Control-plane state reconstructed from log entries (fenced):
    what a freshly elected leader needs to re-arm the cluster."""

    def __init__(self) -> None:
        #: job -> the accepted JobConfig dict (kind="submission")
        self.submissions: Dict[str, Dict[str, Any]] = {}
        #: job -> terminal entry (kind="job_done")
        self.done: Dict[str, Dict[str, Any]] = {}
        #: job -> highest elastic attempt index seen
        self.attempts: Dict[str, int] = {}
        #: job -> newest committed chain checkpoint id (kind="chkp_chain")
        self.chains: Dict[str, str] = {}
        #: takeover history entries, oldest first
        self.takeovers: List[Dict[str, Any]] = []
        #: incident_id -> newest persisted incident transition
        #: (kind="incident") — a successor leader adopts the
        #: non-resolved ones so mid-flight episodes stay open
        self.incidents: Dict[str, Dict[str, Any]] = {}
        self.max_epoch = 0
        self.max_seq = 0
        #: deposed-leader writes rejected during replay (fencing proof)
        self.rejected_stale = 0
        self.entries_applied = 0

    @classmethod
    def from_entries(cls, entries: List[Dict[str, Any]]) -> "ReplayState":
        st = cls()
        for e in sorted(entries, key=lambda e: int(e.get("seq", 0))):
            ep = int(e.get("epoch", 0))
            if ep < st.max_epoch:
                st.rejected_stale += 1
                continue  # fenced: a deposed leader's late write
            st.max_epoch = ep
            st.max_seq = max(st.max_seq, int(e.get("seq", 0)))
            st.entries_applied += 1
            kind = e.get("kind")
            job = e.get("job")
            if kind == "submission" and job:
                st.submissions[job] = e.get("config") or {}
                # a RE-submission of a finished id is a new lifecycle
                st.done.pop(job, None)
            elif kind == "job_done" and job:
                st.done[job] = e
            elif kind == "chkp_chain" and job and e.get("chkp_id"):
                st.chains[job] = str(e["chkp_id"])
            elif kind == "leader_takeover":
                st.takeovers.append(e)
            elif kind == "incident" and e.get("incident_id"):
                # newest transition wins (entries are seq-sorted); the
                # engine's adopt() re-opens the non-resolved ones
                st.incidents[str(e["incident_id"])] = e
            if job and "attempt" in e:
                try:
                    st.attempts[job] = max(st.attempts.get(job, 0),
                                           int(e["attempt"]))
                except (TypeError, ValueError):
                    pass
        return st

    def in_flight(self) -> List[str]:
        """Submissions accepted but never completed — what a takeover
        must re-arm (oldest-accepted first, the original order)."""
        return [j for j in self.submissions if j not in self.done]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "submissions": len(self.submissions),
            "in_flight": self.in_flight(),
            "done": len(self.done),
            "chains": len(self.chains),
            "takeovers": len(self.takeovers),
            "incidents": len(self.incidents),
            "max_epoch": self.max_epoch,
            "max_seq": self.max_seq,
            "rejected_stale": self.rejected_stale,
        }


def replay_file(path: str) -> ReplayState:
    entries, _good, _torn = scan_records(path)
    return ReplayState.from_entries(entries)
