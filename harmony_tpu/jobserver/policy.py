"""Telemetry-driven device policy engine — the loop that SPENDS the sensors.

Every input this engine needs has existed since PRs 8-14 — the per-tenant
ledger (device-seconds, MFU, input-wait, SLO attainment), the doctor's
structured diagnoses, the step-phase critical-path classification, and
the elastic shrink/re-grow fences — but nothing acted on *device*
resources: grow and shrink only triggered on failures, and an SLO breach
merely logged. This module closes the loop (ROADMAP item 1; the
reference's pluggable-policy JobScheduler + ET plan engine, SURVEY.md
L3/L4; elastic replanning per "Elastic Model Aggregation with Parameter
Service" and utilization packing per "Exploring the limits of Concurrency
in ML Training on Google TPUs", PAPERS.md).

Each evaluation window the :class:`PolicyEngine` reads the tenant ledger
(`MetricManager.tenant_ledger` — attainment, MFU, input-wait, and the
critpath ``phase_class``), the doctor's recent diagnoses, and the
scheduler's idle/queued state, and replans placement through the
EXISTING mechanisms — every action is a lockstep elastic fence on a
running ``user.elastic_shrink`` submission, never an in-flight mutation:

* **grow** — an under-SLO tenant whose bound classification says more
  devices genuinely help (compute-bound / balanced / unclassified)
  expands onto idle executors via a re-grow fence;
* **shrink** — under contention (queued arrivals, or an under-SLO
  claimant with nothing idle) a strictly lower-priority tenant holding
  more than one executor degrades to a smaller exclusive carve;
* **pack** — an input- or dispatch-bound victim (the device sits idle
  under it either way) consolidates onto a packable sibling's executors
  as a SHARED grant (ShareAll-style overlap, arbitrated by the TaskUnit
  fair queue), freeing its exclusive carve for the claimant. Comm-bound
  tenants are never packed — model traffic owns their step and an
  overlapping neighbor makes it strictly worse;
* **preempt** — when the victim can neither shrink (one executor) nor
  pack (not idle-classed), a strictly higher-priority claimant still
  wins: the victim surrenders its carve and is re-granted shared on the
  lowest-priority surviving sibling. Priorities come from
  ``TrainerParams.priority``; equal priority never preempts.

Rate limiting is the :class:`ActionGate`: an action fires only after its
signal persisted ``HARMONY_POLICY_CONFIRM`` consecutive evaluations
(hysteresis — a noisy window cannot thrash) and outside the per-subject
AND per-signal ``HARMONY_POLICY_COOLDOWN`` (the input-worker autoscaler
shares the same gate under the ``input_wait`` signal, so device packing
and input-worker scaling can never fight over one stall signal). A
``rebalance_ineffective`` diagnosis (metrics/doctor.py) backs the
subject off multiplicatively.

Every decision is durable and observable: actions record structured
``kind="policy"`` joblog events (which the HA sink tees into the
replicated log, so a takeover inherits the in-flight plan), ride STATUS
(``policy``), render via ``harmony-tpu obs plan``, and tee to the
dashboard as ``kind="policy"`` rows. A deposed HA leader's actions are
rejected at the gate — fenced exactly like its TCP mutations.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_MODE = "HARMONY_POLICY"
ENV_PERIOD = "HARMONY_POLICY_PERIOD"
ENV_COOLDOWN = "HARMONY_POLICY_COOLDOWN"
ENV_CONFIRM = "HARMONY_POLICY_CONFIRM"
ENV_SLO_GROW = "HARMONY_POLICY_SLO_GROW"
ENV_MAX_ACTIONS = "HARMONY_POLICY_MAX_ACTIONS"

#: the engine's action vocabulary — gate sweeps are scoped to it so a
#: SHARED gate's other tenants (the input autoscaler's "up"/"down"
#: keys) keep their streaks
_ACTION_KINDS = frozenset(
    ("grow", "shrink", "pack", "preempt", "async", "protect"))

#: a serving tenant whose windowed p99 is at/over this fraction of its
#: registered SLO is latency-critical: the `protect` action pins its
#: executors out of pack/preempt victim selection
_PROTECT_RATIO = 0.8

#: bound classifications under which a tenant is a PACK victim — the
#: device sits idle beneath it, so overlapping a sibling costs little
_PACKABLE_CLASSES = ("input-bound", "dispatch-bound")
#: ... and under which growing it is pointless (more chips would idle
#: just as hard) or actively harmful (comm scales with devices)
_NO_GROW_CLASSES = ("input-bound", "dispatch-bound", "comm-bound")


def policy_mode() -> str:
    """``HARMONY_POLICY``: ``off`` (no evaluation), ``advise`` (default
    — plans are computed, gated and surfaced, but never executed) or
    ``act`` (plans execute through the elastic fences)."""
    raw = os.environ.get(ENV_MODE, "").strip().lower()
    if raw in ("off", "0", "false"):
        return "off"
    if raw in ("act", "on", "1", "true"):
        return "act"
    return "advise"


def policy_period() -> float:
    """``HARMONY_POLICY_PERIOD`` (default 10 s): seconds between policy
    evaluations (rides the history-scraper cycle, so the effective
    cadence is the next scrape at or after the period)."""
    try:
        return max(0.1, float(os.environ.get(ENV_PERIOD, "") or 10.0))
    except ValueError:
        return 10.0


def policy_cooldown() -> float:
    """``HARMONY_POLICY_COOLDOWN`` (default 30 s): minimum seconds
    between actions on one subject (tenant) and on one SIGNAL — the
    anti-thrash half of the gate."""
    try:
        return max(0.0, float(os.environ.get(ENV_COOLDOWN, "") or 30.0))
    except ValueError:
        return 30.0


def policy_confirm() -> int:
    """``HARMONY_POLICY_CONFIRM`` (default 2): consecutive evaluations a
    signal must persist before its action may fire — the hysteresis
    half of the gate."""
    try:
        return max(1, int(os.environ.get(ENV_CONFIRM, "") or 2))
    except ValueError:
        return 2


def slo_grow_threshold() -> float:
    """``HARMONY_POLICY_SLO_GROW`` (default 0.9): SLO attainment below
    which a tenant is a grow candidate."""
    try:
        return float(os.environ.get(ENV_SLO_GROW, "") or 0.9)
    except ValueError:
        return 0.9


def max_actions_per_window() -> int:
    """``HARMONY_POLICY_MAX_ACTIONS`` (default 1): executed actions per
    evaluation — placement ramps, it does not slosh."""
    try:
        return max(1, int(os.environ.get(ENV_MAX_ACTIONS, "") or 1))
    except ValueError:
        return 1


class ActionGate:
    """Cooldown + hysteresis rate limiter shared by the device policy
    engine and the input-worker autoscaler.

    Keys are ``(subject, action)``; cooldowns apply per SUBJECT and per
    SIGNAL (a fired action on signal ``input_wait`` cools every other
    key on that signal — the device engine and the input autoscaler
    cannot fight over one stall measurement). ``observe`` maintains the
    consecutive-wanting streak; ``fired`` stamps the cooldowns;
    ``back_off`` (driven by ``rebalance_ineffective`` diagnoses)
    multiplies the subject's next cooldown.
    """

    def __init__(self, cooldown_sec: Optional[float] = None,
                 confirm: Optional[int] = None,
                 stale_after: Optional[float] = None,
                 backoff_factor: float = 4.0) -> None:
        self.cooldown_sec = (policy_cooldown() if cooldown_sec is None
                             else float(cooldown_sec))
        self.confirm = policy_confirm() if confirm is None else max(1, int(confirm))
        #: a streak older than this is stale (the engine stopped seeing
        #: the signal) and restarts at 1; default spans ~3 periods so a
        #: single missed evaluation does not reset hysteresis
        self.stale_after = (3.0 * policy_period() if stale_after is None
                            else float(stale_after))
        self.backoff_factor = float(backoff_factor)
        self._lock = threading.Lock()
        self._streak: Dict[Tuple[str, str], Tuple[int, float]] = {}
        self._cool_until: Dict[str, float] = {}  # subject or signal
        self._backoffs: Dict[str, int] = {}      # subject -> count
        self.fired_total = 0

    def observe(self, subject: str, action: str, wanted: bool,
                signal: str = "device",
                now: Optional[float] = None) -> bool:
        """Record one evaluation's view of (subject, action); True when
        the action may fire NOW (streak >= confirm, subject and signal
        both outside cooldown)."""
        now = time.monotonic() if now is None else float(now)
        key = (subject, action)
        with self._lock:
            if not wanted:
                self._streak.pop(key, None)
                return False
            n, last = self._streak.get(key, (0, now))
            n = 1 if (n and now - last > self.stale_after) else n + 1
            self._streak[key] = (n, now)
            if n < self.confirm:
                return False
            for scope in (subject, signal):
                if now < self._cool_until.get(scope, 0.0):
                    return False
            return True

    def fired(self, subject: str, action: str,
              signal: Optional[str] = "device",
              now: Optional[float] = None) -> None:
        """An action executed: reset its streak and start the subject +
        signal cooldowns (scaled by any pending backoff).
        ``signal=None`` cools ONLY the subject — an ADVISORY firing must
        pace its own re-planning without throttling live actuators
        (the input autoscaler) sharing the signal scope."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._streak.pop((subject, action), None)
            cool = self.cooldown_sec
            if self._backoffs.get(subject):
                cool *= self.backoff_factor * self._backoffs[subject]
            self._cool_until[subject] = now + cool
            if signal is not None:
                self._cool_until[signal] = max(
                    self._cool_until.get(signal, 0.0),
                    now + self.cooldown_sec)
            self.fired_total += 1

    def sweep(self, observed: "set[Tuple[str, str]]",
              among: Optional["frozenset[str]"] = None,
              subjects: Optional["set[str]"] = None) -> None:
        """Drop streaks for keys NOT observed this round: hysteresis
        means CONSECUTIVE windows, so a candidate the planner stopped
        surfacing restarts from zero — and a long-lived server never
        accumulates streak entries for tenants long gone. ``among``
        restricts the sweep to keys whose ACTION is in the set — on a
        SHARED gate each loop sweeps only its own action vocabulary
        (the policy engine must never reset the input autoscaler's
        streaks). ``subjects`` restricts it to keys whose SUBJECT is in
        the set — an incremental (overload-degraded) evaluation swept
        only the tenants it actually looked at; the rest keep their
        streaks for their next rotation turn."""
        with self._lock:
            for key in [k for k in self._streak
                        if k not in observed
                        and (among is None or k[1] in among)
                        and (subjects is None or k[0] in subjects)]:
                del self._streak[key]

    def back_off(self, subject: str, now: Optional[float] = None) -> None:
        """A past action on ``subject`` proved ineffective: extend its
        cooldown multiplicatively so the engine stops churning it."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._backoffs[subject] = self._backoffs.get(subject, 0) + 1
            self._cool_until[subject] = max(
                self._cool_until.get(subject, 0.0),
                now + self.cooldown_sec * self.backoff_factor
                * self._backoffs[subject])

    def cooling(self, scope: str, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            return now < self._cool_until.get(scope, 0.0)

    def stats(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            return {
                "cooldown_sec": self.cooldown_sec,
                "confirm": self.confirm,
                "fired_total": self.fired_total,
                "streaks": {f"{s}:{a}": n
                            for (s, a), (n, _) in self._streak.items()},
                "cooling": sorted(k for k, t in self._cool_until.items()
                                  if now < t),
                "backoffs": dict(self._backoffs),
            }


class PolicyAction:
    """One planned placement change. ``executors`` is the target set the
    scheduler will grant the tenant's NEXT elastic attempt; ``shared``
    marks an overlapping (pack/preempt) grant."""

    __slots__ = ("kind", "job", "executors", "shared", "signal", "reason",
                 "evidence", "ts", "executed", "outcome", "epoch",
                 "baseline")

    def __init__(self, kind: str, job: str, executors: List[str],
                 reason: str, evidence: Dict[str, Any],
                 shared: bool = False, signal: str = "device") -> None:
        self.kind = kind
        self.job = job
        self.executors = list(executors)
        self.shared = bool(shared)
        self.signal = signal
        self.reason = reason
        self.evidence = dict(evidence)
        self.ts = 0.0
        self.executed = False
        self.outcome = "planned"
        self.epoch: Optional[int] = None
        self.baseline: Dict[str, Any] = {}

    @property
    def fence_kind(self) -> str:
        """The elastic fence flavor carrying this action: capacity gains
        ride the re-grow fence, every reduction/consolidation the
        shrink fence. `async` keeps the SAME executor set — it rides the
        re-grow fence (no survivors-only retile; the next attempt merely
        relaunches with the async knob pinned). `protect` never reaches
        a fence at all (its actuator is planner-side victim exemption);
        it classes with the non-reductions."""
        return ("regrow" if self.kind in ("grow", "async", "protect")
                else "shrink")

    def to_dict(self) -> Dict[str, Any]:
        return {s: getattr(self, s) for s in self.__slots__}


class PolicyEngine:
    """See the module docstring. Constructor wires the sensor and
    actuator surfaces so the engine itself stays pure and testable:

    * ``scheduler`` — the live :class:`JobScheduler` (idle/queued state,
      ``plan_grant`` targets);
    * ``ledger_fn`` — ``MetricManager.tenant_ledger`` (rows carry
      ``slo``, ``phase_class``, ``mfu``, ``input_wait_frac``);
    * ``tenants_fn`` — actuatable running tenants: ``{job: {"executors",
      "attempt", "priority"}}`` (the pod server's elastic-active view;
      a plain server has none and the engine stays advisory);
    * ``fence_fn(job, kind)`` — schedule a lockstep elastic fence on a
      running attempt, returning the fence epoch or None;
    * ``diagnoses_fn`` — the doctor's recent diagnoses
      (``rebalance_ineffective`` drives backoff);
    * ``leader_ok_fn`` — the HA fence: False on a deposed leader, whose
      actions are rejected, never executed;
    * ``sinks`` — observe every recorded action dict (the jobserver
      tees them to the dashboard).
    """

    def __init__(
        self,
        scheduler: Any,
        ledger_fn: Callable[[], Dict[str, Dict[str, Any]]],
        tenants_fn: Callable[[], Dict[str, Dict[str, Any]]],
        fence_fn: Optional[Callable[[str, str], Optional[int]]] = None,
        diagnoses_fn: Optional[Callable[[], List[Dict[str, Any]]]] = None,
        leader_ok_fn: Optional[Callable[[], bool]] = None,
        gate: Optional[ActionGate] = None,
        sinks: Tuple[Callable[[Dict[str, Any]], None], ...] = (),
    ) -> None:
        self._scheduler = scheduler
        self._ledger_fn = ledger_fn
        self._tenants_fn = tenants_fn
        self._fence_fn = fence_fn
        self._diagnoses_fn = diagnoses_fn
        self._leader_ok_fn = leader_ok_fn
        self.gate = gate or ActionGate()
        self._sinks = tuple(sinks)
        self._lock = threading.Lock()
        self._last_eval = 0.0
        self._last_eval_ms = 0.0
        self._evaluations = 0
        self._actions_total = 0
        self._rejected_total = 0
        self._last_plan: Dict[str, Any] = {}
        self._recent: List[Dict[str, Any]] = []
        #: newest rebalance_ineffective ts already backed off per job —
        #: one diagnosis must back a subject off exactly once
        self._backoff_seen: Dict[str, float] = {}
        #: job -> attempt index at the moment an action fenced it: the
        #: fence lands EPOCHS later, and until the tenant's attempt
        #: advances the plan is in flight — re-fencing it would stack
        #: redundant fences on the same attempt
        self._inflight: Dict[str, int] = {}
        #: job -> monotonic ts of its last fired `protect` action: while
        #: fresh, the tenant's executors are exempt from pack/preempt
        #: victim selection. TTL-scoped (protected_jobs) so a tenant
        #: whose latency recovered — or whose serving traffic stopped —
        #: rejoins the victim pool without an explicit release action
        self._protected: Dict[str, float] = {}

    # -- cadence ---------------------------------------------------------

    def maybe_evaluate(self, jobs: Optional["set[str]"] = None
                       ) -> Optional[Dict[str, Any]]:
        """Evaluate if the period elapsed (the scrape-cycle hook); the
        direct :meth:`evaluate` stays available for tests and benches
        that drive time themselves. ``jobs`` restricts the pass to a
        tenant subset (overload degraded mode)."""
        if policy_mode() == "off":
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._last_eval < policy_period():
                return None
            self._last_eval = now
        return self.evaluate(jobs=jobs)

    # -- one evaluation --------------------------------------------------

    def evaluate(self, now: Optional[float] = None,
                 jobs: Optional["set[str]"] = None) -> Dict[str, Any]:
        """One full plan-and-maybe-act pass; returns the plan (also kept
        as ``last_plan`` for STATUS / ``obs plan``). ``jobs`` restricts
        planning to a tenant subset — the overload ladder's incremental
        degraded mode (jobserver/overload.py): only tenants with fresh
        samples this cycle are considered, and the gate's sweep is
        scoped to them so absent tenants keep their streaks."""
        mode = policy_mode()
        t0 = time.perf_counter()
        now = time.monotonic() if now is None else float(now)
        plan: Dict[str, Any] = {"ts": time.time(), "mode": mode,
                                "considered": [], "actions": []}
        if mode == "off":
            return self._finish(plan, t0)
        rows = self._safe(self._ledger_fn, {})
        tenants = self._safe(self._tenants_fn, {})
        if jobs is not None:
            scope = {str(j) for j in jobs}
            plan["tenant_subset"] = sorted(scope)
            rows = {k: v for k, v in rows.items() if str(k) in scope}
            tenants = {k: v for k, v in tenants.items()
                       if str(k) in scope}
        self._apply_backoffs()
        idle = self._safe(getattr(self._scheduler, "idle_executors",
                                  lambda: []), [])
        # grow takes GRANT units, not loose executors: on a process-
        # carved pod a unit is a whole host process (splitting one
        # between exclusive tenants would break carve disjointness)
        units = self._safe(getattr(self._scheduler, "idle_units",
                                   lambda: [[e] for e in idle]),
                           [[e] for e in idle])
        queued = self._safe(getattr(self._scheduler, "queued_jobs",
                                    lambda: []), [])
        plan["idle_executors"] = list(idle)
        plan["queued"] = [getattr(q, "job_id", str(q)) for q in queued]
        actions = self._decide(rows, tenants, idle, queued,
                               plan["considered"], units)
        budget = max_actions_per_window()
        for a in actions:
            a.ts = time.time()
            with self._lock:
                pending = a.job in self._inflight
            if pending:
                # an earlier action THIS window already fenced the job
                # (cooldown 0 + a multi-action budget could otherwise
                # stack contradictory fences on one attempt)
                a.outcome = "in_flight"
                plan["actions"].append(a.to_dict())
                continue
            ready = self.gate.observe(a.job, a.kind, wanted=True,
                                      signal=a.signal, now=now)
            if not ready:
                # name the actual blocker: an operator chasing a quiet
                # engine must land on the right knob
                a.outcome = ("cooldown"
                             if (self.gate.cooling(a.job, now=now)
                                 or self.gate.cooling(a.signal, now=now))
                             else "hysteresis")
            elif budget <= 0:
                a.outcome = "window_budget"
            else:
                budget -= 1
                self._execute(a, mode, now)
            plan["actions"].append(a.to_dict())
        # hysteresis means CONSECUTIVE windows: candidates the planner
        # stopped surfacing restart their streaks (and never leak).
        # Swept ONLY among this engine's action vocabulary — the input
        # autoscaler's streaks on the shared gate are not ours to reset
        self.gate.sweep({(a.job, a.kind) for a in actions},
                        among=_ACTION_KINDS,
                        subjects=({str(j) for j in jobs}
                                  if jobs is not None else None))
        return self._finish(plan, t0)

    # -- decision --------------------------------------------------------

    def protected_jobs(self, now: Optional[float] = None) -> "set[str]":
        """Tenants currently pinned by a fired `protect` action. Pins
        age out after a few periods — protection must be re-earned from
        live latency, exactly like every other signal-driven streak."""
        now = time.monotonic() if now is None else float(now)
        ttl = max(3.0 * policy_period(), policy_cooldown())
        with self._lock:
            for job in [j for j, ts in self._protected.items()
                        if now - ts > ttl]:
                del self._protected[job]
            return set(self._protected)

    def _decide(self, rows: Dict[str, Any], tenants: Dict[str, Any],
                idle: List[str], queued: List[Any],
                considered: List[Dict[str, Any]],
                units: Optional[List[List[str]]] = None
                ) -> List[PolicyAction]:
        """Pure planning over one window's sensor view (no side
        effects): at most one grow plus at most one contention action
        per window reach the gate."""
        from harmony_tpu.jobserver import elastic as _elastic

        grow_below = slo_grow_threshold()
        cap = _elastic.max_shrinks()

        # prune landed plans (the attempt advanced — or the job left);
        # a still-pending fence keeps its tenant out of this window
        with self._lock:
            for job in list(self._inflight):
                t = tenants.get(job)
                if t is None or int(t.get("attempt", 0)) > self._inflight[job]:
                    del self._inflight[job]
            inflight = set(self._inflight)
        tenants = {j: t for j, t in tenants.items() if j not in inflight}

        def row(job: str) -> Dict[str, Any]:
            return rows.get(job) or {}

        def prio(job: str) -> int:
            return int((tenants.get(job) or {}).get("priority", 0))

        grow_wants: List[Tuple[float, str]] = []
        async_wants: List[Tuple[float, str]] = []
        for job, t in sorted(tenants.items()):
            r = row(job)
            att = (r.get("slo") or {}).get("attainment")
            cls = r.get("phase_class")
            note = {"job": job, "check": "grow", "attainment": att,
                    "class": cls, "priority": prio(job)}
            if att is None or att >= grow_below:
                note["blocked"] = "slo met or unknown"
            elif cls in _NO_GROW_CLASSES:
                note["blocked"] = f"{cls}: more devices would not help"
                # comm-bound is the one no-grow class with a better lever
                # than capacity: overlap the comm instead of buying chips.
                # Only when the worker reported the lever exists for this
                # tenant's (table, trainer, layout) and it is still off —
                # and within the same recovery budget every fenced action
                # respects.
                lever = r.get("async") or {}
                if (cls == "comm-bound" and lever.get("available")
                        and not lever.get("enabled")
                        and int(t.get("attempt", 0)) < cap):
                    note["async_candidate"] = True
                    async_wants.append((att, job))
            elif int(t.get("attempt", 0)) >= cap:
                note["blocked"] = "elastic recovery budget exhausted"
            else:
                grow_wants.append((att, job))
            considered.append(note)
        grow_wants.sort(key=lambda x: (-prio(x[1]), x[0]))
        async_wants.sort(key=lambda x: (-prio(x[1]), x[0]))

        if units is None:
            units = [[e] for e in idle]
        actions: List[PolicyAction] = []
        # latency-sensitive serving tenants near/over their p99 SLO earn
        # a `protect` pin (gated and judged like every other action):
        # while pinned, their executors are exempt from pack/preempt
        # victim selection below
        protected = self.protected_jobs()
        for job in sorted(tenants):
            srv = row(job).get("serving") or {}
            p99 = srv.get("p99_ms")
            slo = srv.get("slo_p99_ms")
            if not srv.get("enabled") or p99 is None or not slo:
                continue
            note = {"job": job, "check": "protect", "p99_ms": p99,
                    "slo_p99_ms": slo}
            if p99 < float(slo) * _PROTECT_RATIO:
                note["blocked"] = "serving latency within SLO headroom"
            else:
                actions.append(PolicyAction(
                    "protect", job,
                    list((tenants.get(job) or {}).get("executors") or ()),
                    signal="serving_latency",
                    reason=(f"serving p99 {p99:.1f}ms at/over "
                            f"{_PROTECT_RATIO:.0%} of its {float(slo):.1f}ms "
                            "SLO: exempting executors from pack/preempt "
                            "victim selection"),
                    evidence={"serving": dict(srv)}))
                # the pin covers THIS cycle's victim sweep too — deciding
                # protect and preempt for the same tenant in one plan
                # would be self-contradictory
                protected.add(job)
            considered.append(note)
        if async_wants:
            # one async action per cycle (same ramp discipline as grow);
            # the executor set is UNCHANGED — the fence relaunches the
            # attempt with the async knob pinned via scheduler.plan_async
            att, job = async_wants[0]
            lever = (row(job).get("async") or {})
            actions.append(PolicyAction(
                "async", job,
                list((tenants.get(job) or {}).get("executors") or ()),
                signal="comm_wait",
                reason=(f"SLO attainment {att:.2f} < {grow_below} and "
                        "comm-bound: enabling bounded-staleness async "
                        "aggregation to overlap pull/push with compute"),
                evidence={"attainment": att, "class": "comm-bound",
                          "async": dict(lever)}))
        if grow_wants and units:
            att, job = grow_wants[0]
            cur = list((tenants.get(job) or {}).get("executors") or ())
            # one GRANT UNIT per action (ramp, don't slosh): a single
            # executor normally, a whole host process on a carved pod
            add = [e for e in units[0] if e not in cur]
            if add:
                actions.append(PolicyAction(
                    "grow", job, cur + add,
                    reason=(f"SLO attainment {att:.2f} < {grow_below} "
                            "with idle capacity"),
                    evidence={"attainment": att,
                              "class": row(job).get("phase_class"),
                              "idle": list(idle), "unit": list(add)}))

        # contention: someone wants capacity nothing idle can satisfy
        claimants: List[Tuple[int, str]] = [
            (int(getattr(getattr(q, "params", None), "priority", 0)),
             getattr(q, "job_id", str(q))) for q in queued]
        if not units:
            claimants += [(prio(j), j) for _, j in grow_wants]
        if not claimants:
            return actions
        claim_prio, claim_job = max(claimants)
        # strictly lower priority only — equal priority never preempts
        # (or shrinks, or packs): contention between peers is the fair
        # queue's job, not the policy's. Tenants under an active
        # `protect` pin are exempt outright: a latency-critical serving
        # tenant's executors are not contention inventory
        victims = sorted(
            (j for j in tenants if prio(j) < claim_prio and j != claim_job
             and j not in protected),
            key=lambda j: (prio(j), j))
        note = {"check": "contention", "claimant": claim_job,
                "claim_priority": claim_prio,
                "victims": list(victims),
                "protected": sorted(protected)}
        considered.append(note)
        for victim in victims:
            t = tenants.get(victim) or {}
            if int(t.get("attempt", 0)) >= cap:
                continue
            execs = list(t.get("executors") or ())
            r = row(victim)
            cls = r.get("phase_class")
            wait = r.get("input_wait_frac")
            packable = (cls in _PACKABLE_CLASSES
                        or (wait is not None and wait >= 0.5))
            if len(execs) > 1:
                keep = execs[:max(1, len(execs) // 2)]
                actions.append(PolicyAction(
                    "shrink", victim, keep,
                    reason=(f"contention: {claim_job} (priority "
                            f"{claim_prio}) waits; shrinking priority "
                            f"{prio(victim)} tenant to {len(keep)} "
                            "executor(s)"),
                    evidence={"claimant": claim_job, "class": cls,
                              "released": execs[len(keep):]}))
                break
            host = self._pack_host(victim, tenants, rows,
                                   exclude=(claim_job,))
            if host is None:
                continue
            kind = "pack" if packable else "preempt"
            signal = ("input_wait" if (packable and cls == "input-bound")
                      else "device")
            actions.append(PolicyAction(
                kind, victim,
                list((tenants.get(host) or {}).get("executors") or ()),
                shared=True, signal=signal,
                reason=(f"contention: {claim_job} (priority {claim_prio}) "
                        f"waits; {kind}ing "
                        + (f"{cls or 'low-utilization'} tenant "
                           if packable else
                           f"priority {prio(victim)} tenant ")
                        + f"onto {host}'s executors (shared)"),
                evidence={"claimant": claim_job, "host": host,
                          "class": cls, "input_wait_frac": wait,
                          "released": execs}))
            break
        return actions

    def _pack_host(self, victim: str, tenants: Dict[str, Any],
                   rows: Dict[str, Any],
                   exclude: Tuple[str, ...] = ()) -> Optional[str]:
        """The sibling a packed/preempted victim overlaps: the
        lowest-priority OTHER tenant that still holds executors,
        preferring one whose own class is packable (two idle-device
        tenants sharing one carve is the cheapest shape). ``exclude``
        bars the CLAIMANT — overlapping the victim onto the tenant the
        action is meant to help would steal back the cycles it frees."""
        best: Optional[Tuple[int, int, str]] = None
        for job, t in sorted(tenants.items()):
            if job == victim or job in exclude or not t.get("executors"):
                continue
            cls = (rows.get(job) or {}).get("phase_class")
            rank = (0 if cls in _PACKABLE_CLASSES else 1,
                    int(t.get("priority", 0)), job)
            if best is None or rank < best:
                best = rank
        return best[2] if best else None

    # -- execution -------------------------------------------------------

    def _execute(self, a: PolicyAction, mode: str, now: float) -> None:
        r = self._safe(self._ledger_fn, {}).get(a.job) or {}
        a.baseline = {"mfu": r.get("mfu"),
                      "attainment": (r.get("slo") or {}).get("attainment"),
                      "samples_per_sec": r.get("samples_per_sec")}
        if self._leader_ok_fn is not None and not self._leader_ok_fn():
            # the HA fence, policy half: a deposed leader must not
            # reshape the pod it no longer owns — same contract as its
            # refused TCP mutations and dropped durable appends
            a.outcome = "rejected_not_leader"
            with self._lock:
                self._rejected_total += 1
            self._record(a)
            return
        if a.kind == "protect":
            # the protect actuator is planner-side state, not a fence:
            # the pin exempts the tenant from victim selection in every
            # later window until it ages out. It executes in advise
            # mode too — exempting a victim moves no executor, so the
            # "advisory plans never reshape the pod" contract holds
            a.executed = True
            a.outcome = "pinned"
            self.gate.fired(a.job, a.kind, signal=a.signal, now=now)
            with self._lock:
                self._actions_total += 1
                self._protected[a.job] = now
            self._record(a)
            return
        if mode != "act" or self._fence_fn is None:
            a.outcome = "advisory"
            # subject-only cooldown (signal=None): the dry run paces its
            # own re-planning but must never throttle the LIVE input
            # autoscaler sharing the input_wait signal scope
            self.gate.fired(a.job, a.kind, signal=None, now=now)
            self._record(a)
            return
        try:
            if a.kind == "async":
                # the async actuator: pin the knob for the next attempt
                # (guarded getattr — an embedding scheduler predating the
                # SPI method downgrades to a knob-less advisory fence)
                plan_async = getattr(self._scheduler, "plan_async", None)
                if plan_async is not None:
                    plan_async(a.job, True)
            self._scheduler.plan_grant(a.job, a.executors, shared=a.shared)
            epoch = self._fence_fn(a.job, a.fence_kind)
        except Exception as e:  # noqa: BLE001 - surfaced in the plan
            self._scheduler.plan_grant(a.job, None)
            a.outcome = f"error: {type(e).__name__}: {e}"[:200]
            self._record(a)
            return
        if epoch is None:
            self._scheduler.plan_grant(a.job, None)
            a.outcome = "skipped_no_fence"
            self._record(a)
            return
        a.executed = True
        a.outcome = "fenced"
        a.epoch = int(epoch)
        self.gate.fired(a.job, a.kind, signal=a.signal, now=now)
        att = int((self._safe(self._tenants_fn, {}).get(a.job)
                   or {}).get("attempt", 0))
        with self._lock:
            self._actions_total += 1
            self._inflight[a.job] = att
        self._record(a)

    def _record(self, a: PolicyAction) -> None:
        """Structured ``kind="policy"`` joblog event (HA-replicated via
        the joblog sink tee) + the bounded recent ring + sinks."""
        d = a.to_dict()
        with self._lock:
            self._recent.append(d)
            del self._recent[:-64]
        try:
            from harmony_tpu.jobserver.joblog import record_event

            record_event(a.job, "policy", action=a.kind,
                         executors=list(a.executors), shared=a.shared,
                         reason=a.reason, outcome=a.outcome,
                         executed=a.executed, fence_epoch=a.epoch,
                         baseline=dict(a.baseline), signal=a.signal)
        except Exception:
            pass  # a joblog hiccup must not fail the control loop
        for sink in self._sinks:
            try:
                sink(d)
            except Exception:
                pass  # sinks are best-effort by contract

    def _apply_backoffs(self) -> None:
        """``rebalance_ineffective`` diagnoses back their tenant off —
        each diagnosis exactly once."""
        if self._diagnoses_fn is None:
            return
        for d in self._safe(self._diagnoses_fn, []):
            if d.get("rule") != "rebalance_ineffective":
                continue
            job = d.get("job")
            # key the dedup on the judged ACTION's timestamp, not the
            # diagnosis's: a re-diagnosis of the same action in a later
            # doctor window must not back the tenant off twice
            ev = (d.get("evidence") or {}).get("policy_event") or {}
            ts = float(ev.get("ts") or d.get("ts") or 0.0)
            if not job or self._backoff_seen.get(job, -1.0) >= ts:
                continue
            self._backoff_seen[job] = ts
            self.gate.back_off(job)

    # -- surfaces --------------------------------------------------------

    def _finish(self, plan: Dict[str, Any], t0: float) -> Dict[str, Any]:
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self._evaluations += 1
            self._last_eval_ms = ms
            self._last_plan = plan
        return plan

    def status(self) -> Dict[str, Any]:
        """The STATUS ``policy`` section / ``obs plan`` payload."""
        with self._lock:
            return {
                "mode": policy_mode(),
                "period_sec": policy_period(),
                "evaluations": self._evaluations,
                "eval_ms": round(self._last_eval_ms, 3),
                "actions_total": self._actions_total,
                "rejected_total": self._rejected_total,
                "last_plan": dict(self._last_plan),
                "recent_actions": list(self._recent)[-16:],
                "gate": self.gate.stats(),
                "protected": sorted(self._protected),
            }

    @staticmethod
    def _safe(fn: Callable[[], Any], default: Any) -> Any:
        try:
            out = fn()
        except Exception:
            return default
        return default if out is None else out
