"""Client-side job submission over the TCP command endpoint.

Parity with the reference's CommandSender (jobserver/client/
CommandSender.java:49-80): app submit tools connect to the long-running
server by localhost TCP and send SUBMIT with the serialized job config, or
SHUTDOWN. The wire format is one newline-terminated JSON object each way
(the reference used a delimiter-framed Tang-serialized string; same idea,
JSON instead of avro/Tang).

Control-plane HA (jobserver/ha.py) makes the endpoint PLURAL: a client
holds the whole replica list (``HARMONY_JOBSERVER_ADDRS``, comma-
separated ``host:port``), retries across it with the standard bounded
backoff (faults/retry.py) when a replica is down or mid-takeover, and
follows a ``NOT_LEADER`` reply's ``leader`` redirect to the current
lease holder — so ``submit``/``status``/``obs`` keep working through a
leader change without the operator editing anything.
"""
from __future__ import annotations

import os
import json
import socket
from typing import Any, Dict, List, Optional, Sequence, Tuple

from harmony_tpu.config.params import JobConfig

#: comma-separated replica submit endpoints (docs/DEPLOY.md §7) — the
#: client-side half of control-plane HA
ENV_ADDRS = "HARMONY_JOBSERVER_ADDRS"


def jobserver_addrs() -> List[str]:
    """The configured replica endpoint list (may be empty)."""
    raw = os.environ.get(ENV_ADDRS, "")
    return [a.strip() for a in raw.split(",") if a.strip()]


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return (host or "127.0.0.1", int(port))


class NotLeaderError(RuntimeError):
    """The replica answered ``NOT_LEADER``; ``leader`` is its redirect
    hint (the lease holder's advertised address), or None."""

    def __init__(self, addr: str, leader: Optional[str]) -> None:
        super().__init__(f"{addr} is not the leader"
                         + (f" (leader: {leader})" if leader else ""))
        self.addr = addr
        self.leader = leader


class ServerBusyError(RuntimeError):
    """The leader answered a structured ``BUSY {retry_after_ms}``
    (admission control shed the command — jobserver/overload.py). A
    busy leader IS STILL THE LEADER: this error must never trigger
    failover to another replica (they would answer NOT_LEADER and the
    walk would land right back here); the client backs off for
    ``retry_after_ms`` (jittered) and retries the same endpoint."""

    def __init__(self, addr: str, retry_after_ms: int) -> None:
        super().__init__(
            f"{addr} is overloaded (BUSY, retry after {retry_after_ms}ms)")
        self.addr = addr
        self.retry_after_ms = int(retry_after_ms)


class CommandSender:
    """One logical client over one or many replicas.

    ``CommandSender(port)`` keeps the original single-endpoint shape;
    ``CommandSender(addrs=[...])`` (or :meth:`from_env`) enables
    failover: each roundtrip walks leader-hint-first through the
    replica list under the bounded retry policy, following NOT_LEADER
    redirects, until a replica accepts or the policy is exhausted."""

    def __init__(self, port: Optional[int] = None, host: str = "127.0.0.1",
                 timeout: float = 60.0,
                 addrs: Optional[Sequence[str]] = None) -> None:
        if port is None and not addrs:
            raise ValueError("CommandSender needs a port or an addr list")
        self.host = host
        self.port = port
        self.timeout = timeout
        self.addrs: List[str] = list(addrs or [])
        if port is not None and not self.addrs:
            self.addrs = [f"{host}:{port}"]
        #: the replica that last answered as leader — tried first
        self._leader_hint: Optional[str] = None

    @classmethod
    def from_env(cls, port: Optional[int] = None,
                 timeout: float = 60.0) -> "CommandSender":
        """HARMONY_JOBSERVER_ADDRS when set, else the given (or
        default 43110) local port."""
        addrs = jobserver_addrs()
        if addrs:
            return cls(addrs=addrs, timeout=timeout)
        return cls(port if port is not None else 43110, timeout=timeout)

    # -- wire ------------------------------------------------------------

    def _roundtrip_one(self, addr: str,
                       payload: Dict[str, Any]) -> Dict[str, Any]:
        from harmony_tpu.faults.partition import fault_connect

        with fault_connect(_parse_addr(addr), role="client",
                           timeout=self.timeout) as s:
            s.sendall((json.dumps(payload) + "\n").encode())
            data = b""
            while not data.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
        if not data.strip():
            raise RuntimeError(
                f"empty reply from job server at {addr} "
                "(connection closed without a response)"
            )
        reply = json.loads(data.decode())
        if isinstance(reply, dict) and reply.get("not_leader"):
            raise NotLeaderError(addr, reply.get("leader"))
        if isinstance(reply, dict) and reply.get("busy"):
            # the busy replica answered authoritatively — remember it
            # as the leader so the backoff retry goes straight back
            self._leader_hint = addr
            raise ServerBusyError(addr,
                                  int(reply.get("retry_after_ms", 250)))
        return reply

    def _candidates(self) -> List[str]:
        """Replicas in try order: last-known leader first, then the
        configured list (stable order; duplicates removed)."""
        out: List[str] = []
        for a in ([self._leader_hint] if self._leader_hint else []) + \
                self.addrs:
            if a and a not in out:
                out.append(a)
        return out

    def _roundtrip_route(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One command against the replica set: every retry attempt
        walks the candidate list (following at most one NOT_LEADER
        redirect per walk); connection failures and standby replies
        back off under the standard bounded policy — a takeover window
        is exactly the transient the retry idiom exists for.

        A :class:`ServerBusyError` ABORTS the walk immediately (it is
        not retryable here): the replica that answered BUSY holds the
        lease, so trying the others would only collect NOT_LEADERs.
        The busy backoff lives one layer up (:meth:`_roundtrip`)."""
        from harmony_tpu.config.params import RetryPolicy
        from harmony_tpu.faults.retry import call_with_retry

        def attempt() -> Dict[str, Any]:
            last: Optional[BaseException] = None
            for addr in self._candidates():
                try:
                    reply = self._roundtrip_one(addr, payload)
                    self._leader_hint = addr
                    return reply
                except NotLeaderError as e:
                    last = e
                    if e.leader and e.leader not in (addr,):
                        try:
                            reply = self._roundtrip_one(e.leader, payload)
                            self._leader_hint = e.leader
                            return reply
                        except (OSError, NotLeaderError,
                                ValueError) as e2:
                            last = e2
                except (OSError, ValueError) as e:
                    last = e
            raise ConnectionError(
                f"no jobserver replica accepted {payload.get('command')}: "
                f"{type(last).__name__ if last else '?'}: {last}")

        if self.port is not None and len(self.addrs) <= 1:
            # legacy single fixed endpoint (port ctor): keep the
            # original fail-fast shape — tests and scripts rely on an
            # immediate refused/NOT_LEADER error, on EVERY command of
            # the sender's lifetime. An ``addrs`` ctor of any length
            # opts into failover + redirect following.
            return self._roundtrip_one(self.addrs[0], payload)
        return call_with_retry(
            attempt, RetryPolicy.from_env(), op="client.roundtrip",
            retryable=(ConnectionError,),
        )

    def _roundtrip(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """The busy-honoring roundtrip: BUSY {retry_after_ms} replies
        back off (the server's hint is the floor under the policy's
        jittered schedule) and retry the SAME leader — never failover;
        failover stays reserved for CONNECT errors inside
        :meth:`_roundtrip_route`. Bounded by the standard retry
        policy: a persistently-overloaded control plane surfaces as a
        RetryError instead of an infinite client spin."""
        import time as _time

        from harmony_tpu.config.params import RetryPolicy
        from harmony_tpu.faults.retry import call_with_retry, jitter_rng

        policy = RetryPolicy.from_env()
        hint_ms = [0]

        def once() -> Dict[str, Any]:
            try:
                return self._roundtrip_route(payload)
            except ServerBusyError as e:
                hint_ms[0] = e.retry_after_ms
                raise

        def pause(delay: float) -> None:
            # jitter_rng: the swappable source faults.retry uses for its
            # own backoff, so a seeded chaos replay pins BOTH schedules
            floor = (hint_ms[0] / 1000.0) * (
                1.0 + policy.jitter * jitter_rng().random())
            _time.sleep(max(delay, floor))

        return call_with_retry(
            once, policy, op="client.busy",
            retryable=(ServerBusyError,), sleep=pause,
        )

    # -- commands --------------------------------------------------------

    def send_job_submit_command(self, config: JobConfig) -> Dict[str, Any]:
        """SUBMIT carries the caller's span context beside the config (the
        TraceInfo-in-the-message pattern, tracing/span.py): a submission
        made inside ``trace_span("cli.submit")`` yields ONE trace_id from
        this client through the jobserver's dispatch, the pod legs and
        the remote workers' spans. None outside any span — the field is
        simply absent and the server roots a fresh trace."""
        from harmony_tpu.tracing.span import wire_context

        msg: Dict[str, Any] = {"command": "SUBMIT", "conf": config.to_dict()}
        wire = wire_context()
        if wire is not None:
            msg["trace"] = wire
        return self._roundtrip(msg)

    def send_status_command(self) -> Dict[str, Any]:
        return self._roundtrip({"command": "STATUS"})

    def send_wait_command(self, job_id: str,
                          timeout: float = 30.0) -> Dict[str, Any]:
        """One bounded WAIT poll on a submission's result."""
        return self._roundtrip({"command": "WAIT", "job_id": job_id,
                                "timeout": timeout})

    def wait_result(self, job_id: str, timeout: float = 300.0,
                    poll: float = 15.0) -> Dict[str, Any]:
        """Follow ONE submission to completion across replicas: WAIT
        polls ride the failover roundtrip, so a leader change mid-job
        redirects to the successor — which re-armed the same submission
        from the durable log and resolves it under the same job id.
        Returns the result dict; raises on job failure or deadline."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"job {job_id} did not complete within {timeout}s")
            try:
                reply = self.send_wait_command(
                    job_id, timeout=min(poll, max(0.5, remaining)))
            except (ConnectionError, RuntimeError):
                # takeover window (no leader yet) — keep polling until
                # the deadline; the retry policy already backed off
                _time.sleep(min(1.0, max(0.0, remaining)))
                continue
            if reply.get("ok") and reply.get("done"):
                return reply.get("result") or {}
            if not reply.get("ok"):
                if not reply.get("known", True):
                    # the successor may still be replaying/re-arming —
                    # an unknown id right after failover is transient
                    _time.sleep(min(1.0, max(0.0, remaining)))
                    continue
                raise RuntimeError(
                    f"job {job_id} failed: {reply.get('error')}")

    def send_pod_reshard_command(
        self, job_id: str, src: str, dst: str, num_blocks: int, epoch: int
    ) -> Dict[str, Any]:
        """Operator-initiated live migration of a running pod job (the
        reference's driver-side moveBlocks, reachable from ops tooling)."""
        return self._roundtrip({
            "command": "POD_RESHARD", "job_id": job_id, "src": src,
            "dst": dst, "num_blocks": num_blocks, "epoch": epoch,
        })

    def send_serving_command(self) -> Dict[str, Any]:
        """Resolve (and start on demand) the leader's serving endpoint
        (harmony_tpu/serving): leader-gated server-side, so the reply's
        ``host:port`` always names the replica that owns live tables."""
        return self._roundtrip({"command": "SERVING"})

    def send_shutdown_command(self) -> Dict[str, Any]:
        return self._roundtrip({"command": "SHUTDOWN"})


def submit_job(config: JobConfig, port: int) -> Dict[str, Any]:
    reply = CommandSender(port).send_job_submit_command(config)
    if not reply.get("ok"):
        raise RuntimeError(f"submit failed: {reply.get('error')}")
    return reply


def shutdown_server(port: int) -> None:
    CommandSender(port).send_shutdown_command()
