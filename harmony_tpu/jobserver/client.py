"""Client-side job submission over the TCP command endpoint.

Parity with the reference's CommandSender (jobserver/client/
CommandSender.java:49-80): app submit tools connect to the long-running
server by localhost TCP and send SUBMIT with the serialized job config, or
SHUTDOWN. The wire format is one newline-terminated JSON object each way
(the reference used a delimiter-framed Tang-serialized string; same idea,
JSON instead of avro/Tang).
"""
from __future__ import annotations

import json
import socket
from typing import Any, Dict

from harmony_tpu.config.params import JobConfig


class CommandSender:
    def __init__(self, port: int, host: str = "127.0.0.1", timeout: float = 60.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout

    def _roundtrip(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        with socket.create_connection((self.host, self.port), timeout=self.timeout) as s:
            s.sendall((json.dumps(payload) + "\n").encode())
            data = b""
            while not data.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
        if not data.strip():
            raise RuntimeError(
                f"empty reply from job server at {self.host}:{self.port} "
                "(connection closed without a response)"
            )
        return json.loads(data.decode())

    def send_job_submit_command(self, config: JobConfig) -> Dict[str, Any]:
        """SUBMIT carries the caller's span context beside the config (the
        TraceInfo-in-the-message pattern, tracing/span.py): a submission
        made inside ``trace_span("cli.submit")`` yields ONE trace_id from
        this client through the jobserver's dispatch, the pod legs and
        the remote workers' spans. None outside any span — the field is
        simply absent and the server roots a fresh trace."""
        from harmony_tpu.tracing.span import wire_context

        msg: Dict[str, Any] = {"command": "SUBMIT", "conf": config.to_dict()}
        wire = wire_context()
        if wire is not None:
            msg["trace"] = wire
        return self._roundtrip(msg)

    def send_status_command(self) -> Dict[str, Any]:
        return self._roundtrip({"command": "STATUS"})

    def send_pod_reshard_command(
        self, job_id: str, src: str, dst: str, num_blocks: int, epoch: int
    ) -> Dict[str, Any]:
        """Operator-initiated live migration of a running pod job (the
        reference's driver-side moveBlocks, reachable from ops tooling)."""
        return self._roundtrip({
            "command": "POD_RESHARD", "job_id": job_id, "src": src,
            "dst": dst, "num_blocks": num_blocks, "epoch": epoch,
        })

    def send_shutdown_command(self) -> Dict[str, Any]:
        return self._roundtrip({"command": "SHUTDOWN"})


def submit_job(config: JobConfig, port: int) -> Dict[str, Any]:
    reply = CommandSender(port).send_job_submit_command(config)
    if not reply.get("ok"):
        raise RuntimeError(f"submit failed: {reply.get('error')}")
    return reply


def shutdown_server(port: int) -> None:
    CommandSender(port).send_shutdown_command()
