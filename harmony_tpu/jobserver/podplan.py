"""Pod-wide reconfiguration plans, applied at deterministic epoch points.

Plan-driven migration on a RUNNING pod job (ref: the driver-initiated
MoveInitMsg flow, MigrationExecutor.java:107-253) cannot run from an
orchestrator thread the way single-process jobs do: a reshard is a
collective transfer, and one process dispatching it off-schedule wedges
the pod. Instead the leader broadcasts the plan over the control plane
(PodJobServer.schedule_pod_reshard) and EVERY process applies the same
move at the same LOGICAL point — the chief worker's epoch hook, which
lockstep guarantees fires at identical epochs everywhere. This module is
the per-process registry between the control plane and the hook.

Scheduling contract: the apply epoch must be comfortably ahead of the
job's current epoch on every process — a plan landing mid-epoch-E while
some processes already passed their epoch-E hook would be applied at
different epochs (divergent meshes, wedged collectives). Plans applied
late (first hook at epoch > plan epoch) are applied immediately and
consistently ONLY when the message arrived before any process crossed
the plan epoch; give multi-epoch lead.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List

_LOCK = threading.Lock()
_PLANS: Dict[str, List[Dict[str, Any]]] = {}


def schedule(job_id: str, plan: Dict[str, Any]) -> None:
    """Register a plan: {"epoch": int, "src": executor_id,
    "dst": executor_id, "num_blocks": int}."""
    with _LOCK:
        _PLANS.setdefault(job_id, []).append(dict(plan))


def take(job_id: str, epoch_idx: int) -> List[Dict[str, Any]]:
    """Pop (in schedule order) every plan whose epoch is due at
    ``epoch_idx`` — called from the chief worker's epoch hook."""
    with _LOCK:
        plans = _PLANS.get(job_id)
        if not plans:
            return []
        due = [p for p in plans if int(p.get("epoch", 0)) <= epoch_idx]
        _PLANS[job_id] = [p for p in plans if p not in due]
        return due


def next_epoch(job_id: str) -> "int | None":
    """Smallest scheduled (not yet taken) plan epoch for the job — the
    worker's window clamp (see WorkerTasklet.pending_plan_epoch)."""
    with _LOCK:
        plans = _PLANS.get(job_id)
        if not plans:
            return None
        return min(int(p.get("epoch", 0)) for p in plans)


def clear(job_id: str) -> None:
    with _LOCK:
        _PLANS.pop(job_id, None)
