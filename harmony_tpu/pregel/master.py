"""PregelMaster — the BSP superstep loop over device-resident tables.

Parity with the reference's Pregel runtime (SURVEY.md §2.8):

  * vertex table + TWO message tables swapped every superstep
    (ref: PregelDriver.java:53-111, MessageManager currentTable/nextTable),
  * per-superstep worker computation with TaskUnits COMP/SEND/SYNC
    (ref: PregelWorkerTask.java:53-120),
  * the master ends the job when every vertex has voted to halt and no
    messages are in flight (ref: PregelMaster.java:44-110,
    SuperstepControlMsg/SuperstepResultMsg),
  * message combining per destination (ref: pregel/combiner/).

TPU-first: one superstep is ONE jitted SPMD step over the job's mesh —
gather source states along edges, compute edge messages, segment-combine
into the next message table (an XLA scatter with the combiner's fold), and
run the vectorized vertex compute. The two message DenseTables double-buffer
exactly like the reference's table swap; vertex state/messages shard over
the model axis.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from harmony_tpu.config.params import TableConfig
from harmony_tpu.pregel.computation import Computation
from harmony_tpu.pregel.graph import Graph
from harmony_tpu.table.table import DenseTable, TableSpec


class PregelMaster:
    def __init__(
        self,
        graph: Graph,
        computation: Computation,
        mesh: Mesh,
        max_supersteps: int = 100,
        taskunit: Optional[Any] = None,
        job_id: str = "pregel",
        dispatch_turn: Optional[Any] = None,
    ) -> None:
        if getattr(computation, "undirected", False):
            graph = graph.undirected()
        self.graph = graph
        self.comp = computation
        self.mesh = mesh
        self.max_supersteps = max_supersteps
        self.taskunit = taskunit
        self.job_id = job_id
        # Cross-job pod unit scope (runtime/podunits.py): under share-all
        # tenancy every superstep dispatch holds a leader-granted unit so
        # its enqueues cannot interleave with another tenant's (the
        # single dispatch thread keeps the per-process unit sequence
        # deterministic). None outside pods.
        self.dispatch_turn = dispatch_turn
        V = graph.num_vertices
        update = {"add": "add", "min": "min", "max": "max"}[computation.combiner]

        def table(name: str, vshape, init_update: str) -> DenseTable:
            return DenseTable(
                TableSpec(
                    TableConfig(
                        table_id=f"{job_id}:{name}",
                        capacity=V,
                        value_shape=vshape,
                        num_blocks=min(V, 64),
                        update_fn=init_update,
                    )
                ),
                mesh,
            )

        self.vertex_table = table("vertices", (computation.state_dim,), "assign")
        # the two swapped message tables (current <-> next)
        self._msg_tables = [table("msg-a", (), update), table("msg-b", (), update)]
        self._has_msg = [
            table("has-a", (), "max"),
            table("has-b", (), "max"),
        ]
        self._cur = 0
        self.superstep_count = 0
        # seed vertex state (ref: vertex table bulk-loaded before superstep 0)
        init = computation.initial_state(V)
        # table-level write_all: the old per-call jax.jit(spec.write_all)
        # lambdas (one INSIDE the message-table loop) built fresh jit
        # wrappers per invocation, defeating the jit cache
        self.vertex_table.write_all(init)
        # seed message tables with the combiner identity ("no message")
        for mt in self._msg_tables:
            mt.write_all(jnp.full((V,), computation.msg_identity, jnp.float32))
        self._build()

    # -- compiled superstep ----------------------------------------------

    def _build(self) -> None:
        comp = self.comp
        g = self.graph
        vspec = self.vertex_table.spec
        mspec = self._msg_tables[0].spec
        hspec = self._has_msg[0].spec
        src = jnp.asarray(g.src)
        dst = jnp.asarray(g.dst)
        weight = jnp.asarray(g.weight)
        identity = jnp.float32(comp.msg_identity)

        def superstep(varr, cur_msg_arr, cur_has_arr, nxt_msg_arr, nxt_has_arr, step):
            state = vspec.pull_all(varr)                     # [V, S]
            msg = mspec.pull_all(cur_msg_arr)                # [V]
            has_msg = hspec.pull_all(cur_has_arr) > 0.5      # [V]
            new_state, halt = comp.compute(step, state, msg, has_msg)
            # active vertices send along out-edges (halted send nothing)
            sending = ~halt                                   # [V]
            edge_vals = comp.edge_message(step, new_state[src], weight)
            edge_on = sending[src]
            edge_vals = jnp.where(edge_on, edge_vals, identity)
            # combine per destination into the NEXT message table
            nxt_msgs = jnp.full((g.num_vertices,), identity, jnp.float32)
            if comp.combiner == "add":
                nxt_msgs = nxt_msgs.at[dst].add(edge_vals)
            elif comp.combiner == "min":
                nxt_msgs = nxt_msgs.at[dst].min(edge_vals)
            else:
                nxt_msgs = nxt_msgs.at[dst].max(edge_vals)
            nxt_has = (
                jnp.zeros((g.num_vertices,), jnp.float32)
                .at[dst]
                .max(edge_on.astype(jnp.float32))
            )
            num_msgs = jnp.sum(nxt_has)
            all_halted = jnp.all(halt)
            # reset the CURRENT tables for reuse as next-next (the swap)
            cur_msg_reset = jnp.full_like(msg, identity)
            cur_has_reset = jnp.zeros_like(nxt_has)
            return (
                vspec.write_all(varr, new_state),
                mspec.write_all(cur_msg_arr, cur_msg_reset),
                hspec.write_all(cur_has_arr, cur_has_reset),
                mspec.write_all(nxt_msg_arr, nxt_msgs),
                hspec.write_all(nxt_has_arr, nxt_has),
            ), (all_halted, num_msgs)

        shardings = (
            self.vertex_table.sharding,
            self._msg_tables[0].sharding,
            self._has_msg[0].sharding,
            self._msg_tables[1].sharding,
            self._has_msg[1].sharding,
        )
        self._superstep = jax.jit(
            superstep,
            out_shardings=(shardings, None),
            donate_argnums=(0, 1, 2, 3, 4),
        )

    # -- the loop (SuperstepControlMsg flow) ------------------------------

    def run(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        for step in range(self.max_supersteps):
            cur, nxt = self._cur, 1 - self._cur
            tables = [
                self.vertex_table,
                self._msg_tables[cur],
                self._has_msg[cur],
                self._msg_tables[nxt],
                self._has_msg[nxt],
            ]
            with self._turn(), self._tu("COMP"):
                all_halted, num_msgs = DenseTable.apply_step_multi(
                    tables, self._superstep, jnp.int32(step)
                )
            self.superstep_count = step + 1
            self._cur = nxt  # the table swap (MessageManager.swap)
            # D2H reads of replicated scalars: every process reads the
            # SAME values, so the loop-break decision stays lockstep
            if bool(all_halted) and float(num_msgs) == 0.0:
                break
        return {
            "supersteps": self.superstep_count,
            "wall_sec": time.perf_counter() - t0,
            "vertex_values": self._collect_values(),
        }

    def _collect_values(self) -> np.ndarray:
        """Final vertex values on the host. On a multi-process mesh the
        table's shards span hosts, so the pull replicates first (one
        all-gather every process dispatches in lockstep, inside a unit);
        single-process meshes read the sharded pull directly."""
        from harmony_tpu.parallel.mesh import mesh_spans_processes

        spans = mesh_spans_processes(self.mesh)
        with self._turn():
            arr = self.vertex_table.pull_array(replicated=spans)
        return np.asarray(arr)

    def close(self) -> None:
        """Release every device-resident table (vertex + both message
        double-buffers). The one place that knows the full table set — job
        entities must call this instead of reaching into internals."""
        for t in [self.vertex_table, *self._msg_tables, *self._has_msg]:
            t.drop()

    def _tu(self, kind: str):
        if self.taskunit is None:
            import contextlib

            return contextlib.nullcontext()
        return self.taskunit.scope(kind)

    def _turn(self):
        if self.dispatch_turn is None:
            import contextlib

            return contextlib.nullcontext()
        return self.dispatch_turn()
