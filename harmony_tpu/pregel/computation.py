"""The vertex computation SPI.

Parity with the reference's ``Computation`` + ``Vertex`` API
(pregel/graph/api/: compute(vertex, messages), sendMessage, voteToHalt) and
its message combiners (pregel/combiner/).

TPU-first reshaping: per-vertex Java objects become vectorized pure
functions over the whole partition —

  * ``compute(superstep, state, msg, has_msg)`` — all vertices at once;
    returns the new state and a vote-to-halt mask (the reference's
    voteToHalt). Halted vertices are revived by incoming messages, exactly
    like Pregel semantics.
  * ``edge_message(superstep, src_state, weight)`` — the value each edge
    carries from its source, vectorized over edges; the framework combines
    messages per destination with the declared ``combiner`` ("add"/"min"/
    "max" — the reference's MessageCombiner), realized as one XLA
    segment-reduction instead of per-vertex message queues.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


class Computation:
    combiner: str = "add"        # fold for messages to one destination
    state_dim: int = 1           # per-vertex state width
    # identity for the combiner; also the "no message" value
    msg_identity: float = 0.0
    # True = messages must flow along BOTH directions of every edge; the
    # master symmetrizes the graph before running (Graph.undirected).
    undirected: bool = False

    def initial_state(self, num_vertices: int) -> jnp.ndarray:
        """[num_vertices, state_dim] initial vertex values."""
        raise NotImplementedError

    def compute(
        self,
        superstep: jnp.ndarray,
        state: jnp.ndarray,      # [V, state_dim]
        msg: jnp.ndarray,        # [V] combined incoming message
        has_msg: jnp.ndarray,    # [V] bool
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (new_state, vote_to_halt [V] bool)."""
        raise NotImplementedError

    def edge_message(
        self,
        superstep: jnp.ndarray,
        src_state: jnp.ndarray,  # [E, state_dim] gathered source states
        weight: jnp.ndarray,     # [E]
    ) -> jnp.ndarray:
        """[E] message values carried along each edge."""
        raise NotImplementedError
