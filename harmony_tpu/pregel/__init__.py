from harmony_tpu.pregel.graph import Graph
from harmony_tpu.pregel.computation import Computation
from harmony_tpu.pregel.master import PregelMaster

__all__ = ["Graph", "Computation", "PregelMaster"]
