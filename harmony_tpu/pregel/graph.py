"""Graph container for Pregel jobs.

The reference stores vertices in an ET vertex table partitioned across
workers (pregel/graph/ + PregelDriver.java:53-111). Here the graph is
edge-list arrays (src, dst, weight) plus per-vertex out-degrees — the layout
message scatter needs; vertex *state* lives in a DenseTable (see master.py).
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class Graph:
    def __init__(
        self,
        num_vertices: int,
        src: np.ndarray,
        dst: np.ndarray,
        weight: Optional[np.ndarray] = None,
    ) -> None:
        if src.shape != dst.shape:
            raise ValueError("src/dst must align")
        if len(src) and (
            (src >= num_vertices).any() or (dst >= num_vertices).any()
            or (src < 0).any() or (dst < 0).any()
        ):
            raise ValueError("edge endpoint out of range")
        self.num_vertices = num_vertices
        self.src = src.astype(np.int32)
        self.dst = dst.astype(np.int32)
        self.weight = (
            weight.astype(np.float32) if weight is not None else np.ones(len(src), np.float32)
        )
        self.out_degree = np.bincount(self.src, minlength=num_vertices).astype(np.float32)

    @property
    def num_edges(self) -> int:
        return len(self.src)

    def undirected(self) -> "Graph":
        """Symmetrized copy: every edge also exists reversed (same weight).
        Required by computations that flood in both directions (e.g.
        connected components' HashMin — a directed edge alone would only
        propagate labels forward)."""
        return Graph(
            self.num_vertices,
            np.concatenate([self.src, self.dst]),
            np.concatenate([self.dst, self.src]),
            np.concatenate([self.weight, self.weight]),
        )

    @staticmethod
    def from_edge_list(num_vertices: int, edges) -> "Graph":
        """edges: iterable of (src, dst) or (src, dst, weight)."""
        arr = [tuple(e) for e in edges]
        src = np.array([e[0] for e in arr])
        dst = np.array([e[1] for e in arr])
        w = (
            np.array([e[2] for e in arr], np.float32)
            if arr and len(arr[0]) > 2
            else None
        )
        return Graph(num_vertices, src, dst, w)


def random_graph(
    num_vertices: int, avg_degree: int = 4, seed: int = 0, weighted: bool = False
) -> Graph:
    """Synthetic digraph for examples/CLI presets: every vertex gets
    ``avg_degree`` out-edges to uniform targets (self-loops filtered),
    optionally with uniform [0.5, 1.5) weights (for shortest-path demos)."""
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(num_vertices), avg_degree)
    dst = rng.integers(0, num_vertices, size=src.shape)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    w = rng.uniform(0.5, 1.5, size=src.shape).astype(np.float32) if weighted else None
    return Graph(num_vertices, src, dst, w)


def load_edge_list(path: str, num_vertices: int = 0) -> Graph:
    """Parse a whitespace edge-list file (``src dst [weight]`` per line,
    ``#`` comments) — the CLI analogue of the reference's vertex-file input."""
    src, dst, w = [], [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) not in (2, 3):
                raise ValueError(f"{path}:{lineno}: expected 'src dst [weight]'")
            src.append(int(parts[0]))
            dst.append(int(parts[1]))
            if len(parts) > 2:
                w.append(float(parts[2]))
            elif w:
                raise ValueError(
                    f"{path}:{lineno}: unweighted edge in a weighted file "
                    "(every line must carry a weight, or none)"
                )
    if w and len(w) != len(src):
        raise ValueError(f"{path}: only {len(w)} of {len(src)} edges weighted")
    n = num_vertices or (max(max(src), max(dst)) + 1 if src else 0)
    return Graph(
        n, np.asarray(src), np.asarray(dst),
        np.asarray(w, np.float32) if w else None,
    )
