"""Graph container for Pregel jobs.

The reference stores vertices in an ET vertex table partitioned across
workers (pregel/graph/ + PregelDriver.java:53-111). Here the graph is
edge-list arrays (src, dst, weight) plus per-vertex out-degrees — the layout
message scatter needs; vertex *state* lives in a DenseTable (see master.py).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class Graph:
    def __init__(
        self,
        num_vertices: int,
        src: np.ndarray,
        dst: np.ndarray,
        weight: Optional[np.ndarray] = None,
    ) -> None:
        if src.shape != dst.shape:
            raise ValueError("src/dst must align")
        if len(src) and (
            (src >= num_vertices).any() or (dst >= num_vertices).any()
            or (src < 0).any() or (dst < 0).any()
        ):
            raise ValueError("edge endpoint out of range")
        self.num_vertices = num_vertices
        self.src = src.astype(np.int32)
        self.dst = dst.astype(np.int32)
        self.weight = (
            weight.astype(np.float32) if weight is not None else np.ones(len(src), np.float32)
        )
        self.out_degree = np.bincount(self.src, minlength=num_vertices).astype(np.float32)

    @property
    def num_edges(self) -> int:
        return len(self.src)

    @staticmethod
    def from_edge_list(num_vertices: int, edges) -> "Graph":
        """edges: iterable of (src, dst) or (src, dst, weight)."""
        arr = [tuple(e) for e in edges]
        src = np.array([e[0] for e in arr])
        dst = np.array([e[1] for e in arr])
        w = (
            np.array([e[2] for e in arr], np.float32)
            if arr and len(arr[0]) > 2
            else None
        )
        return Graph(num_vertices, src, dst, w)
