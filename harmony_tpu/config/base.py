"""Typed, serializable configuration system.

The reference wires every knob through Tang ``@NamedParameter`` classes,
serializes whole config graphs to strings, ships them across processes, and
re-injects them (ref: ETDolphinLauncher.java:119-201, JobServerDriver.java:
243-245, TaskletRuntime forked injectors). This module is the TPU build's
equivalent: dataclass-based configs with

  * a class registry so polymorphic nested configs round-trip through JSON
    (``_type`` discriminator),
  * dotted-path symbol references for user callables/classes (trainers,
    update functions, parsers) — the analogue of Tang binding an
    implementation class by name.

Configs are plain data: JSON in, JSON out, no pickling, safe to send over the
control plane.
"""
from __future__ import annotations

import dataclasses
import importlib
import json
from typing import Any, Dict, Type, TypeVar

_REGISTRY: Dict[str, type] = {}

T = TypeVar("T")


def register_config(cls: Type[T]) -> Type[T]:
    """Register a dataclass config type under its class name."""
    _REGISTRY[cls.__name__] = cls
    return cls


def config(cls: Type[T]) -> Type[T]:
    """Decorator: make ``cls`` a frozen-ish dataclass config and register it."""
    dc = dataclasses.dataclass(cls)
    return register_config(dc)


def symbol_name(obj: Any) -> str:
    """Dotted import path for a module-level callable/class."""
    module = getattr(obj, "__module__", None)
    qualname = getattr(obj, "__qualname__", None)
    if not module or not qualname or "<locals>" in qualname:
        raise ValueError(f"not an importable module-level symbol: {obj!r}")
    return f"{module}:{qualname}"


def resolve_symbol(path: str) -> Any:
    """Inverse of :func:`symbol_name`."""
    module, _, qual = path.partition(":")
    obj: Any = importlib.import_module(module)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


def _encode(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        d = {"_type": type(value).__name__}
        for f in dataclasses.fields(value):
            d[f.name] = _encode(getattr(value, f.name))
        return d
    if isinstance(value, dict):
        enc = {k: _encode(v) for k, v in value.items()}
        if "_type" in value:
            # Escape user dicts that happen to carry the discriminator key so
            # they can't collide with (or hijack) registered config types.
            return {"_type": "__raw_dict__", "value": enc}
        return enc
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    return value


def _decode(value: Any) -> Any:
    if isinstance(value, dict):
        if "_type" in value:
            if value["_type"] == "__raw_dict__":
                return {k: _decode(v) for k, v in value["value"].items()}
            cls = _REGISTRY.get(value["_type"])
            if cls is None:
                raise KeyError(f"unregistered config type {value['_type']!r}")
            kwargs = {k: _decode(v) for k, v in value.items() if k != "_type"}
            return cls(**kwargs)
        return {k: _decode(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode(v) for v in value]
    return value


class ConfigBase:
    """Mixin giving dataclass configs JSON round-trip and copy-with-changes."""

    def to_dict(self) -> Dict[str, Any]:
        return _encode(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> Any:
        return _decode(d)

    @staticmethod
    def from_json(s: str) -> Any:
        return _decode(json.loads(s))

    def replace(self: T, **changes: Any) -> T:
        return dataclasses.replace(self, **changes)  # type: ignore[type-var]
