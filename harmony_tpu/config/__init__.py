from harmony_tpu.config.base import (
    ConfigBase,
    config,
    register_config,
    resolve_symbol,
    symbol_name,
)
from harmony_tpu.config.params import (
    ExecutorConfig,
    JobConfig,
    RemoteAccessConfig,
    TableConfig,
    TaskletConfig,
    TrainerParams,
)

__all__ = [
    "ConfigBase",
    "config",
    "register_config",
    "resolve_symbol",
    "symbol_name",
    "ExecutorConfig",
    "JobConfig",
    "RemoteAccessConfig",
    "TableConfig",
    "TaskletConfig",
    "TrainerParams",
]
