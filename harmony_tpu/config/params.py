"""Concrete config schemas: table / executor / tasklet / job.

Mirrors the reference's typed builders — TableConfiguration.java:36-214,
ExecutorConfiguration.java:26-72, RemoteAccessConfiguration, TaskletConfiguration,
and the Dolphin job parameter set (dolphin/DolphinParameters.java) — rebuilt as
serializable dataclasses (see config/base.py for the Tang analogy).
"""
from __future__ import annotations

import dataclasses
from dataclasses import field
from typing import Any, Dict, List, Optional, Tuple

from harmony_tpu.config.base import ConfigBase, config

# Reference default: NumTotalBlocks def 1024
# (services/et/.../configuration/parameters/NumTotalBlocks.java).
DEFAULT_NUM_BLOCKS = 1024


@config
class TableConfig(ConfigBase):
    """Schema of one elastic table (ref: TableConfiguration.java:36-214).

    The reference stores opaque K/V pairs with codecs; on TPU values are typed
    arrays so the schema carries value shape/dtype instead of codec classes.
    ``is_ordered`` selects range vs hash partitioning exactly as the
    reference's ``IsOrderedTable`` does (TableConfiguration.java:42-45).
    """

    table_id: str
    capacity: int                      # number of addressable keys [0, capacity)
    value_shape: Tuple[int, ...] = ()  # per-key value shape; () = scalar
    dtype: str = "float32"
    num_blocks: int = DEFAULT_NUM_BLOCKS
    is_ordered: bool = True            # range partitioner; False = hash
    is_mutable: bool = True
    update_fn: str = "add"             # name in table.update registry
    # Sparse key domain: back the table with a capacity-bounded device hash
    # table (DeviceHashTable) — getOrInit admits ANY non-negative int32 key,
    # ``capacity`` bounds SLOTS, not the key domain. Dense tables
    # (sparse=False) preallocate exactly [0, capacity).
    sparse: bool = False
    # Optional bulk-load source (ref: FilePath / BulkDataLoader binding).
    input_path: Optional[str] = None
    parser: Optional[str] = None       # dotted path of DataParser

    def __post_init__(self) -> None:
        if self.capacity <= 0:
            raise ValueError("capacity must be positive")
        if self.num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        if self.num_blocks > self.capacity:
            # Clamp HERE (not in the partitioner) so the config is the single
            # source of truth for block count — BlockManager, checkpoints and
            # storage must all agree on block ids.
            object.__setattr__(self, "num_blocks", self.capacity)
        if isinstance(self.value_shape, list):
            object.__setattr__(self, "value_shape", tuple(self.value_shape))


@config
class RetryPolicy(ConfigBase):
    """Bounded retry with exponential backoff + jitter for transient
    infrastructure faults (block-migration transport legs, checkpoint
    block I/O, the isolated orbax worker's pipe ops — see
    harmony_tpu.faults.retry.call_with_retry).

    The schedule: attempt, sleep ``base_delay_sec``, attempt, sleep
    ``base_delay_sec * multiplier`` ... capped at ``max_delay_sec``, for
    at most ``max_attempts`` attempts; each sleep is stretched by up to
    ``jitter`` (fraction) of itself so retrying peers don't stampede a
    recovering endpoint in sync. Exhaustion raises RetryError, which
    carries the ``infra_suspect`` marker the pod's auto-resume keys on.
    """

    max_attempts: int = 4
    base_delay_sec: float = 0.05
    max_delay_sec: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_sec < 0 or self.max_delay_sec < 0:
            raise ValueError("delays must be non-negative")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1 (backoff, not decay)")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError("jitter is a fraction in [0, 1]")

    _ENV_FIELDS = (
        ("max_attempts", "HARMONY_RETRY_MAX_ATTEMPTS", int),
        ("base_delay_sec", "HARMONY_RETRY_BASE_DELAY", float),
        ("max_delay_sec", "HARMONY_RETRY_MAX_DELAY", float),
        ("multiplier", "HARMONY_RETRY_MULTIPLIER", float),
        ("jitter", "HARMONY_RETRY_JITTER", float),
    )

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Defaults overridden by ``HARMONY_RETRY_*`` env vars — an env
        knob (like HARMONY_CHKP_BACKEND) precisely so every pod process
        inherits the same policy without per-layer plumbing."""
        import os

        kv = {}
        for field_name, var, cast in cls._ENV_FIELDS:
            raw = os.environ.get(var)
            if raw is not None:
                kv[field_name] = cast(raw)
        return cls(**kv)


@config
class RemoteAccessConfig(ConfigBase):
    """Host-side op-queue knobs (ref: RemoteAccessConfiguration: CommQueueSize,
    NumCommThreads). On TPU the data plane is XLA collectives, but the host
    control plane still runs queued ops for sparse/irregular access."""

    num_comm_threads: int = 4
    queue_size: int = 1024


@config
class ExecutorConfig(ConfigBase):
    """Per-executor resources (ref: ExecutorConfiguration.java:26-72 and the
    README operating point: 5 executors x 128 MB x 1 core). An "executor" here
    is one device (chip) slot of the pod mesh plus its host-side runtime."""

    num_devices: int = 1
    remote_access: RemoteAccessConfig = field(default_factory=RemoteAccessConfig)
    # TaskUnit slots per executor (ref: LocalTaskUnitScheduler.java:36-37).
    cpu_slots: int = 1
    net_slots: int = 2
    # Heterogeneous resource specs (ref: HeterogeneousEvalManager.java:40-70
    # matching allocations to per-request node names/sizes): restrict this
    # request to devices of a kind (case-insensitive substring, e.g.
    # "v5 lite") and/or one host process of a multi-host pod. None = any.
    device_kind: Optional[str] = None
    process_index: Optional[int] = None


@config
class TaskletConfig(ConfigBase):
    """One unit of computation submitted to an executor
    (ref: TaskletConfiguration; Tasklet.java:24-36)."""

    tasklet_id: str
    tasklet_class: str            # dotted path, resolved at start
    user_params: Dict[str, Any] = field(default_factory=dict)


@config
class TrainerParams(ConfigBase):
    """Dolphin hyper-parameter block (ref: DolphinParameters.java:26-195).

    ``num_mini_batches`` plays the role of NumWorkerBlocks: an epoch is
    partitioned into exactly this many batches (= input-table blocks per
    worker in the reference, ETTrainingDataProvider.java:38-75).
    """

    num_epochs: int = 1
    num_mini_batches: int = 10
    clock_slack: int = 0              # SSP staleness bound; 0 = BSP
    step_size: float = 0.1
    decay_rate: float = 0.9
    decay_period: int = 5
    num_trainer_threads: int = 1
    model_cache_enabled: bool = False
    # Model-checkpoint chaining during training (ref: ModelChkpManager,
    # dolphin/core/master/ModelChkpManager.java:40-80). 0 = disabled;
    # N = snapshot the model table every N epochs.
    model_chkp_period: int = 0
    # Defer offline evaluation of the chained checkpoints to JobServer
    # shutdown (ref: JobServerDriver graceful shutdown runs deferred model
    # evaluation, JobServerDriver.java:178-214).
    offline_model_eval: bool = False
    # Comm/comp split probe period in epochs (WorkerTasklet._probe_comm —
    # the fused-mode analogue of the reference's per-op pull/push timers,
    # ModelAccessor.java:33-49). Each probe costs several BLOCKING device
    # round-trips, which on a remote-attached chip is real wall time: jobs
    # that feed an elasticity optimizer want 1; latency-sensitive jobs can
    # raise the period or disable with 0 (the last split stays in effect).
    comm_probe_period: int = 1
    # Asynchronous host->device input pipeline (dolphin/prefetch.py): a
    # producer thread assembles batches and stages their device transfers
    # ahead of the compute loop, overlapping host input work with device
    # compute. Ring depth follows the worker's in-flight cap (shallow
    # under TaskUnit contention). Default ON; disable for A/B parity runs
    # — losses are bit-identical either way for a fixed seed — or on
    # hosts where the extra thread is unwelcome. Ignored (synchronous
    # path kept) under pod lockstep / multi-process meshes, where a
    # background thread's device_puts would break the deterministic
    # pod-wide dispatch order.
    input_prefetch: bool = True
    # Disaggregated input-data service (harmony_tpu/inputsvc): pull
    # assembled, shard-ready batches from the shared input workers
    # instead of assembling them in-process, so same-dataset tenants
    # share ONE epoch assembly through the cross-tenant batch cache.
    # Default OFF (opt-in rollout); the process-wide
    # HARMONY_INPUT_SERVICE env knob (0/1) overrides for every job, and
    # HARMONY_INPUT_SERVICE_ADDR points trainers at a standalone service
    # process. Requires a wire-safe dataset identity (user.data_fn /
    # data_args); jobs without one keep in-process assembly. Losses are
    # bit-identical either way for a fixed seed — the service replays
    # the same epoch permutation the local provider draws — and every
    # service failure degrades to in-process assembly after bounded
    # retry (docs/INPUT_PIPELINE.md §"Input service").
    input_service: bool = False
    # Scheduling priority (jobserver/policy.py): under device contention
    # the policy engine shrinks, packs or preempts strictly LOWER-
    # priority tenants to satisfy higher-priority claimants (queued
    # arrivals, under-SLO growers). Equal priority never preempts.
    # Higher = more important; 0 = best-effort (the default).
    priority: int = 0
    # Per-job throughput SLO (metrics/accounting.py): the samples/sec
    # this job is expected to sustain. 0 = no target. When a worker
    # sustains < 90% of the target across a window of epochs it records
    # a structured joblog event (kind="slo") and the tenant ledger's
    # attainment gauge (harmony_tenant_slo_attainment) carries the
    # achieved/target ratio — the signal the ROADMAP-item-4 policy loop
    # scales on. The process-wide HARMONY_SLO_SPS env knob overrides
    # for every job (operator floor enforcement).
    target_samples_per_sec: float = 0.0
    # Fused device hot path (dolphin/worker.py): compile each batch's
    # PULL -> COMP -> PUSH into ONE jitted program with the table buffer
    # donated (the dense SPMD fast path's contract). Default ON; OFF
    # selects the unfused per-phase fallback — three separately-dispatched
    # programs with a host round-trip between phases (the reference's
    # ModelAccessor shape), bit-identical losses for a fixed seed, and
    # REAL measured pull/push/comp phase seconds instead of the fused
    # path's probe-derived split. The process-wide HARMONY_FUSED_STEP env
    # knob (0/1) overrides for operator rollback. Multi-process meshes
    # keep the fused path regardless: the unfused host round-trip would
    # need every process to materialize cross-host shards.
    fused_step: bool = True
    # Bounded-staleness async aggregation (dolphin/worker.py): overlap
    # step k's PUSH+PULL with step k+1's COMP by routing the comm phases
    # through a dedicated comm thread that applies deltas and republishes
    # the pulled view while the device computes on the previous view.
    # Default OFF = today's synchronous contract. staleness_bound caps
    # the applied-update lag a compute step may observe: compute for
    # step k hard-blocks until at least k - staleness_bound deltas have
    # been applied. Bound 0 fully serializes and is BIT-identical to the
    # synchronous unfused path (pinned by tests/test_async_step.py).
    # Process-wide HARMONY_ASYNC_STEP / HARMONY_STALENESS_BOUND env
    # knobs override for operator rollback; elastic fences drain the
    # in-flight window before snapshotting so the (seed, epoch,
    # step-apply-order) replay contract holds. See
    # docs/DEVICE_HOT_PATH.md §Async step mode.
    async_step: bool = False
    staleness_bound: int = 0
    app_params: Dict[str, Any] = field(default_factory=dict)


@config
class JobConfig(ConfigBase):
    """A full job submission (ref: the serialized conf DolphinJobLauncher
    assembles and ships over TCP; jobserver/DolphinJobLauncher.java)."""

    job_id: str
    app_type: str                      # "dolphin" | "pregel"
    trainer: Optional[str] = None      # dotted path of Trainer subclass
    # Metric-driven elasticity for this job (ref: the per-job Optimizer
    # binding behind ETOptimizationOrchestrator, and the -optimizer flag):
    # "homogeneous" | "add_one_server" | "delete_one_server" | a dotted
    # path resolving to an Optimizer class/factory. None = static sharding.
    optimizer: Optional[str] = None
    optimizer_period: float = 5.0      # seconds between optimization rounds
    update_fn: str = "add"
    tables: List[TableConfig] = field(default_factory=list)
    params: TrainerParams = field(default_factory=TrainerParams)
    num_workers: int = 0               # 0 = all executors (ref SchedulerImpl: all)
    user: Dict[str, Any] = field(default_factory=dict)
