"""Generic PS-table trainer for functional pytree models.

Any model exposing ``init(key) -> params`` and a pure loss over a batch
trains through the framework's elastic-table substrate with this one
Trainer: the flattened params pytree lives in a range-partitioned
DenseTable (rows of ``row_width`` f32), pull="all" re-assembles it each
batch, and the push folds the update through the table's additive fold —
so checkpointing, live migration and multi-tenancy apply to ANY model
family for free. The LM (models/transformer.py TransformerTrainer) and
ViT (models/vit.py ViTTrainer) are thin subclasses binding the model and
its batch->loss signature.

Stateful optimizers (harmony_tpu.dolphin.optim): momentum/Adam state
occupies extra row sections of the SAME table — ``[params | m | v |
counter row]`` — so optimizer state checkpoints, reshards and migrates
with the parameters (the reference has no shared-optimizer-state
mechanism at all; its trainers are plain SGD).
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from harmony_tpu.config.params import TableConfig
from harmony_tpu.dolphin.trainer import Trainer, TrainerContext


class PyTreeTrainer(Trainer):
    pull_mode = "all"

    #: default table id; subclasses override
    default_table_id = "model"
    #: model config dataclass; subclasses set it and implement build_model,
    #: then inherit the config-vs-flat-kwargs constructor (flat kwargs keep
    #: JobConfig.app_params JSON-serializable for the TCP submit path)
    config_cls: Any = None

    def build_model(self, config: Any) -> Any:
        raise NotImplementedError

    def __init__(
        self,
        config: Any = None,
        row_width: int = 1024,
        step_size: float = 0.1,
        seed: int = 0,
        optimizer: str = "sgd",
        **config_kwargs,
    ) -> None:
        from harmony_tpu.dolphin import optim

        if config is None:
            config = self.config_cls(**config_kwargs)
        elif config_kwargs:
            raise TypeError("pass either config= or flat config kwargs, not both")
        self.config = config
        self.model = self.build_model(config)
        self.row_width = row_width
        self.step_size = step_size
        self.seed = seed
        self.optimizer = optimizer
        self.num_state_slots = optim.num_slots(optimizer)  # validates name
        template = jax.eval_shape(
            lambda: self.model.init(jax.random.PRNGKey(0))
        )
        flat, self._unravel = ravel_pytree(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), template)
        )
        self.num_params = flat.shape[0]
        self.num_rows = -(-self.num_params // row_width)

    # -- model binding (subclass hooks) -----------------------------------

    def loss_on_batch(self, params, batch) -> jnp.ndarray:
        """Pure scalar loss for one batch; subclasses bind the model's
        batch signature here."""
        raise NotImplementedError

    def eval_metrics(self, params, batch) -> Dict[str, jnp.ndarray]:
        return {"loss": self.loss_on_batch(params, batch)}

    # -- table schema -----------------------------------------------------

    @property
    def capacity(self) -> int:
        # param rows + one section per state slot + the step-counter row
        extra = 1 if self.num_state_slots else 0
        return self.num_rows * (1 + self.num_state_slots) + extra

    def model_table_config(
        self, table_id: str = "", num_blocks: int = 0
    ) -> TableConfig:
        return TableConfig(
            table_id=table_id or self.default_table_id,
            capacity=self.capacity,
            value_shape=(self.row_width,),
            num_blocks=num_blocks or max(self.capacity // 8, 1),
            is_ordered=True,
            update_fn="add",
        )

    # -- lifecycle --------------------------------------------------------

    def init_global_settings(self, ctx: TrainerContext) -> None:
        params = self.model.init(jax.random.PRNGKey(self.seed))
        flat, _ = ravel_pytree(params)
        ctx.model_table.multi_put(
            list(range(self.num_rows)), np.asarray(self._to_rows(flat))
        )
        # m/v sections and the counter row start (and stay, until the first
        # push) at the table's init value 0.

    # -- pure parts -------------------------------------------------------

    def _to_rows(self, flat: jnp.ndarray) -> jnp.ndarray:
        pad = self.num_rows * self.row_width - self.num_params
        return jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)]
        ).reshape(self.num_rows, self.row_width)

    def _section(self, model: jnp.ndarray, i: int) -> jnp.ndarray:
        """Flat [num_params] view of row section i (0=params, 1=m, 2=v)."""
        rows = model[i * self.num_rows:(i + 1) * self.num_rows]
        return rows.reshape(-1)[: self.num_params]

    def hyperparams(self) -> Dict[str, float]:
        return {"lr": self.step_size}

    def compute(self, model, batch, hyper):
        from harmony_tpu.dolphin import optim

        pflat = self._section(model, 0)
        params = self._unravel(pflat)
        loss, grads = jax.value_and_grad(self.loss_on_batch)(params, batch)
        gflat, _ = ravel_pytree(grads)
        slots = self.num_state_slots
        m = self._section(model, 1) if slots >= 1 else jnp.zeros_like(pflat)
        v = self._section(model, 2) if slots >= 2 else jnp.zeros_like(pflat)
        t = model[-1, 0] + 1.0 if slots else jnp.asarray(1.0)
        new_p, new_m, new_v = optim.apply(
            self.optimizer, pflat, gflat, m, v, t, hyper
        )
        sections = [self._to_rows(new_p - pflat)]
        if slots >= 1:
            sections.append(self._to_rows(new_m - m))
        if slots >= 2:
            sections.append(self._to_rows(new_v - v))
        delta = jnp.concatenate(sections)
        if slots:
            counter = jnp.zeros((1, self.row_width), delta.dtype).at[0, 0].set(1.0)
            delta = jnp.concatenate([delta, counter])
        return delta, {"loss": loss}

    def evaluate(self, model, batch) -> Dict[str, jnp.ndarray]:
        params = self._unravel(self._section(model, 0))
        return self.eval_metrics(params, batch)
