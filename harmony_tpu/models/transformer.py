"""Decoder-only transformer LM — the framework's flagship neural model.

Design (TPU-first, no reference counterpart — the reference has no attention
models, SURVEY.md §5.7):

  * **Functional params pytree** (dicts of arrays), f32 masters; activations
    run in ``config.dtype`` (bf16 on hardware) so matmuls hit the MXU at
    full rate.
  * **Attention tiers**: single-chip uses the Pallas flash kernel
    (harmony_tpu.ops.attention); sequence-parallel training uses ring
    attention (harmony_tpu.ops.ring) or the all-to-all head-scatter
    scheme (harmony_tpu.ops.ulysses, ``sp_attn="a2a"``) inside
    ``shard_map`` over the mesh's "seq" axis; the blockwise scan is the
    differentiable/any-backend tier.
  * **PS-table integration**: :class:`TransformerTrainer` flattens the
    pytree into a range-partitioned DenseTable ([rows, row_width]) so the
    LM trains through the same Trainer SPI / WorkerTasklet / elastic-table
    machinery as every classic app — checkpointing, live resharding and
    multi-tenant scheduling apply to the LM for free.
  * **make_sp_train_step**: the long-context path — batch sharded over
    "data", sequence sharded over "seq"; grads are psum'd over both axes and
    params stay replicated, so a step is ONE compiled SPMD program whose
    collectives (ring ppermute + grad psum) ride ICI.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from harmony_tpu.ops.attention import blockwise_attention, flash_attention
from harmony_tpu.ops.ring import ring_attention
from harmony_tpu.ops.ulysses import a2a_attention
from harmony_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 256
    dtype: Any = jnp.float32        # activation dtype (bf16 on hardware)
    attn: str = "auto"              # "auto" | "flash" | "blockwise"
    sp_attn: str = "ring"           # sequence-parallel tier: "ring" | "a2a"
    remat: bool = False             # rematerialize each layer's activations
                                    # on the backward pass (HBM for FLOPs)
    # Mixture-of-Experts FFN (Switch-style, models/moe.py): 0 = dense.
    # Every ``moe_every``-th block swaps its FFN for a top-1-routed expert
    # bank; the Switch aux load-balance loss joins the CE at
    # ``moe_aux_weight``.
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.5
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        from harmony_tpu.models.common import validate_attn

        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.sp_attn not in ("ring", "a2a"):
            raise ValueError(f"unknown sp_attn {self.sp_attn!r}")
        if self.moe_experts and self.moe_every < 1:
            raise ValueError("moe_every must be >= 1")
        validate_attn(self.attn)

    def is_moe_layer(self, i: int) -> bool:
        """Block i uses the MoE FFN (the last of every ``moe_every`` group —
        Switch interleaves dense and expert blocks)."""
        return bool(self.moe_experts) and (i % self.moe_every
                                           == self.moe_every - 1)

    @property
    def moe_cfg(self):
        from harmony_tpu.models.moe import MoEConfig

        return MoEConfig(num_experts=self.moe_experts, d_model=self.d_model,
                         d_ff=self.d_ff,
                         capacity_factor=self.moe_capacity_factor)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


from harmony_tpu.models.common import rms_norm as _norm  # noqa: E402


class TransformerLM:
    """Pure-functional decoder-only LM: ``init`` -> params, ``apply`` ->
    logits, ``loss`` -> mean next-token cross-entropy."""

    def __init__(self, config: TransformerConfig) -> None:
        self.config = config

    # -- params ----------------------------------------------------------

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        k_emb, k_pos, *k_layers = jax.random.split(rng, 2 + cfg.n_layers)
        d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff

        from harmony_tpu.models.common import dense_init as dense

        layers = []
        for i, kl in enumerate(k_layers):
            ks = jax.random.split(kl, 4)
            layer = {
                "ln1": jnp.ones((d,), jnp.float32),
                "wqkv": dense(ks[0], (d, 3 * d)),
                "wo": dense(ks[1], (d, d)),
                "ln2": jnp.ones((d,), jnp.float32),
            }
            if cfg.is_moe_layer(i):
                from harmony_tpu.models.moe import init_moe_params

                layer["moe"] = init_moe_params(ks[2], cfg.moe_cfg)
            else:
                layer["w1"] = dense(ks[2], (d, f))
                layer["w2"] = dense(ks[3], (f, d))
            layers.append(layer)
        return {
            "embed": jax.random.normal(k_emb, (cfg.vocab_size, d), jnp.float32) * 0.02,
            "pos": jax.random.normal(k_pos, (cfg.max_seq, d), jnp.float32) * 0.02,
            "ln_f": jnp.ones((d,), jnp.float32),
            "layers": layers,
        }

    def init_numpy(self, seed: int = 0) -> Dict[str, Any]:
        """``init`` with numpy arrays and NO jax op — same layout and
        scaling, usable where touching jax would initialize a backend that
        might hang (e.g. the graft entry point on a wedged transport).
        Values differ from ``init`` (different RNG); structure is pinned
        against ``init`` by test."""
        cfg = self.config
        rng = np.random.default_rng(seed)
        d, f = cfg.d_model, cfg.d_ff

        def dense(shape):
            return (rng.standard_normal(shape)
                    * shape[0] ** -0.5).astype(np.float32)

        layers = []
        for i in range(cfg.n_layers):
            layer = {
                "ln1": np.ones((d,), np.float32),
                "wqkv": dense((d, 3 * d)),
                "wo": dense((d, d)),
                "ln2": np.ones((d,), np.float32),
            }
            if cfg.is_moe_layer(i):
                E = cfg.moe_experts
                layer["moe"] = {
                    "router": dense((d, E)),
                    "w1": (rng.standard_normal((E, d, f)) * d ** -0.5
                           ).astype(np.float32),
                    "w2": (rng.standard_normal((E, f, d)) * f ** -0.5
                           ).astype(np.float32),
                }
            else:
                layer["w1"] = dense((d, f))
                layer["w2"] = dense((f, d))
            layers.append(layer)
        return {
            "embed": (0.02 * rng.standard_normal(
                (cfg.vocab_size, d))).astype(np.float32),
            "pos": (0.02 * rng.standard_normal(
                (cfg.max_seq, d))).astype(np.float32),
            "ln_f": np.ones((d,), np.float32),
            "layers": layers,
        }

    # -- forward ---------------------------------------------------------

    def _attention(self, q, k, v, axis_name: Optional[str]):
        cfg = self.config
        if axis_name is not None:
            sp = a2a_attention if cfg.sp_attn == "a2a" else ring_attention
            return sp(q, k, v, axis_name=axis_name, causal=True)
        S = q.shape[2]
        from harmony_tpu.models.common import resolve_attn

        attn = resolve_attn(cfg.attn, S, block=128)  # matches blocks below
        if attn == "flash":
            return flash_attention(q, k, v, causal=True,
                                   block_q=min(128, S), block_k=min(128, S))
        return blockwise_attention(q, k, v, causal=True)

    def _block(self, x, layer, axis_name: Optional[str],
               moe_axis: Optional[str] = None):
        """One pre-norm decoder block — the shared body of ``apply`` and
        the pipeline-parallel stage fn. Returns ``(x, aux)``: aux is the
        Switch load-balance loss when the block carries an MoE FFN, 0
        otherwise. ``moe_axis`` = expert-parallel mesh axis (see
        ffn_apply)."""
        cfg = self.config
        B, S = x.shape[0], x.shape[1]
        d, h, hd = cfg.d_model, cfg.n_heads, cfg.head_dim
        xn = _norm(x, layer["ln1"].astype(cfg.dtype))
        qkv = xn @ layer["wqkv"].astype(cfg.dtype)              # [B, S, 3d]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, S, h, hd).transpose(0, 2, 1, 3)
        o = self._attention(to_heads(q), to_heads(k), to_heads(v), axis_name)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, d)
        x = x + o @ layer["wo"].astype(cfg.dtype)
        xn = _norm(x, layer["ln2"].astype(cfg.dtype))
        out, aux = ffn_apply(cfg, layer, xn, moe_axis=moe_axis)
        return x + out, aux

    def apply(
        self,
        params: Dict[str, Any],
        tokens: jnp.ndarray,              # [B, S] int32 (LOCAL shard under SP)
        axis_name: Optional[str] = None,  # seq-parallel ring axis (shard_map)
        pos_offset: Any = 0,              # global position of tokens[:, 0]
    ) -> jnp.ndarray:
        logits, _ = self._apply_with_aux(params, tokens, axis_name, pos_offset)
        return logits

    def _apply_with_aux(self, params, tokens, axis_name=None, pos_offset=0,
                        moe_axis=None):
        """apply + the summed MoE aux loss (0 for dense configs)."""
        cfg = self.config
        x = _embed_in(cfg, params["embed"], params["pos"], tokens, pos_offset)

        def block(x, layer):
            return self._block(x, layer, axis_name, moe_axis=moe_axis)

        if cfg.remat:
            # Per-layer rematerialization: the backward recomputes each
            # block's activations instead of keeping them — activation HBM
            # drops from O(n_layers * B * S * d) to O(B * S * d), bought
            # with one extra forward pass of FLOPs (the MXU has headroom;
            # HBM usually doesn't).
            block = jax.checkpoint(block)
        aux = jnp.asarray(0.0, jnp.float32)
        for layer in params["layers"]:
            x, a = block(x, layer)
            aux = aux + a
        x = _norm(x, params["ln_f"].astype(cfg.dtype))
        # Weight-tied readout, f32 logits for a stable softmax.
        return x.astype(jnp.float32) @ params["embed"].T, aux

    def loss(self, params, tokens, axis_name=None) -> jnp.ndarray:
        """Mean next-token cross-entropy over the (single-device) batch,
        plus the weighted MoE load-balance aux for expert configs."""
        logits, aux = self._apply_with_aux(params, tokens[:, :-1],
                                           axis_name=axis_name)
        ce = _next_token_ce(logits, tokens[:, 1:])
        if self.config.moe_experts:
            return ce + self.config.moe_aux_weight * aux
        return ce


def _next_token_ce(logits, targets) -> jnp.ndarray:
    """Mean next-token cross-entropy — ONE implementation shared by the
    single-device loss and the pipeline-parallel loss (the SP path's
    _masked_ce differs: psum-reduced masked mean over sharded axes)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def ffn_apply(cfg, layer, xn, no_drop: bool = False,
              moe_axis: Optional[str] = None):
    """Dense or MoE FFN on [..., d] activations — the ONE dense/MoE
    dispatch shared by training blocks and the decode path. Returns
    ``(out, aux)``. ``no_drop`` lifts the expert capacity to cover every
    token (decode routes tiny per-step batches where the training
    capacity_factor would drop tokens whenever two rows share an expert,
    letting one sequence degrade another's output). ``moe_axis`` is the
    expert-parallel mesh axis: expert params are sharded on their leading
    dim and token buckets move over ICI via all_to_all (moe_ffn)."""
    if "moe" in layer:
        import dataclasses as _dc

        from harmony_tpu.models.moe import moe_ffn

        mcfg = cfg.moe_cfg
        if no_drop:
            mcfg = _dc.replace(mcfg, capacity_factor=float(mcfg.num_experts))
        flat = xn.reshape(-1, cfg.d_model)
        out, aux = moe_ffn(layer["moe"], flat, mcfg, axis_name=moe_axis)
        return out.reshape(xn.shape), aux
    out = jax.nn.gelu(xn @ layer["w1"].astype(cfg.dtype)) \
        @ layer["w2"].astype(cfg.dtype)
    return out, jnp.asarray(0.0, jnp.float32)


def _embed_in(cfg, embed, pos, tokens, pos_offset=0) -> jnp.ndarray:
    """Token+position embedding in activation dtype — shared by apply and
    the pipeline-parallel path."""
    idx = pos_offset + jnp.arange(tokens.shape[1])
    return (embed[tokens] + pos[idx]).astype(cfg.dtype)


# ---------------------------------------------------------------------------
# Sequence-parallel training step (the long-context path)
# ---------------------------------------------------------------------------

def _lm_targets_and_mask(tokens: jnp.ndarray):
    """Global next-token targets + loss mask, built BEFORE sharding so a
    shard's last position targets the next shard's first token."""
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    mask = jnp.concatenate(
        [jnp.ones_like(tokens[:, 1:], jnp.float32),
         jnp.zeros_like(tokens[:, :1], jnp.float32)], axis=1
    )
    return targets, mask


def _masked_ce(logits, targets, mask, psum_axes):
    """Masked mean next-token cross-entropy, psum-reduced over the sharded
    batch/sequence axes."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    tot = lax.psum((-ll * mask).sum(), psum_axes)
    cnt = lax.psum(mask.sum(), psum_axes)
    return tot / cnt


def make_sp_train_step(
    model: TransformerLM,
    mesh,
    learning_rate: float = 0.1,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    donate: bool = True,
):
    """Build a jitted SPMD train step: ``step(params, tokens) ->
    (new_params, loss)`` with batch over ``data_axis`` and sequence over
    ``seq_axis`` (ring attention). ``tokens`` is the GLOBAL [B, S] array;
    params are replicated and stay replicated (grad psum over both axes).
    """
    axes = (data_axis, seq_axis)

    def local_step(params, tokens, targets, mask):
        S_loc = tokens.shape[1]
        offset = lax.axis_index(seq_axis) * S_loc

        def loss_fn(p):
            logits, aux = model._apply_with_aux(
                p, tokens, axis_name=seq_axis, pos_offset=offset
            )
            loss = _masked_ce(logits, targets, mask, axes)
            if model.config.moe_experts:
                # aux is per-shard (each shard routes its local tokens):
                # mean over shards keeps the weight comparable to the
                # single-device objective
                loss = loss + model.config.moe_aux_weight \
                    * lax.pmean(aux, axes)
            return loss

        # Params enter replicated (unvarying) and the loss is psum-reduced,
        # so shard_map's typed autodiff already inserts the cross-device
        # gradient psum during transposition — grads come back replicated.
        # (An explicit psum here would multiply the gradient by the device
        # count.)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params = jax.tree.map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), params, grads
        )
        return new_params, loss

    tok_spec = P(data_axis, seq_axis)

    # donate=True (default): the update aliases params in place instead of
    # holding old AND new parameter buffers live across the step (2x param
    # HBM on TPU). Callers needing the pre-step params pass donate=False.
    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(params, tokens):
        targets, mask = _lm_targets_and_mask(tokens)
        return jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), tok_spec, tok_spec, tok_spec),
            out_specs=(P(), P()),
        )(params, tokens, targets, mask)

    return step


# ---------------------------------------------------------------------------
# Combined data x sequence x tensor parallel training step
# ---------------------------------------------------------------------------

def tp_param_specs(n_layers: int, model_axis: str) -> Dict[str, Any]:
    """PartitionSpec tree for the tensor-parallel param layout (wqkv split
    into wq/wk/wv): Megatron-style column-parallel in-projections
    (``P(None, model)``) and row-parallel out-projections
    (``P(model, None)``); everything else replicated."""
    layer = {
        "ln1": P(), "ln2": P(),
        "wq": P(None, model_axis), "wk": P(None, model_axis),
        "wv": P(None, model_axis),
        "wo": P(model_axis, None),
        "w1": P(None, model_axis), "w2": P(model_axis, None),
    }
    return {
        "embed": P(), "pos": P(), "ln_f": P(),
        "layers": [dict(layer) for _ in range(n_layers)],
    }


def to_tp_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Convert the LM's packed-wqkv param tree to the TP layout (wq/wk/wv
    separate so each can shard cleanly on its output dim)."""
    layers = []
    for layer in params["layers"]:
        wq, wk, wv = jnp.split(layer["wqkv"], 3, axis=-1)
        layers.append({
            "ln1": layer["ln1"], "ln2": layer["ln2"],
            "wq": wq, "wk": wk, "wv": wv,
            "wo": layer["wo"], "w1": layer["w1"], "w2": layer["w2"],
        })
    return {"embed": params["embed"], "pos": params["pos"],
            "ln_f": params["ln_f"], "layers": layers}


def make_parallel_train_step(
    model: TransformerLM,
    mesh,
    learning_rate: float = 0.1,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    model_axis: str = "model",
    donate: bool = True,
):
    """Build the full 3-axis SPMD train step: batch over ``data_axis``,
    sequence over ``seq_axis`` (ring attention), and tensor parallelism
    over ``model_axis`` (column-parallel wq/wk/wv+w1 with heads split
    across shards, row-parallel wo/w2 with a psum back to replicated
    activations — the Megatron decomposition, expressed in shard_map so
    XLA schedules every collective on ICI).

    Returns ``(step, shard_params)``: ``shard_params(params)`` places a
    replicated param tree into the TP layout/sharding; ``step(tp_params,
    tokens) -> (new_tp_params, loss)`` takes the GLOBAL token matrix.

    Gradient flow: the loss is psum-reduced over (data, seq); TP-sharded
    leaves get their gradients locally (each shard owns its slice), while
    replicated leaves (embeddings, norms) are transposed through the
    forward psums, so shard_map's typed autodiff inserts the model-axis
    gradient psum exactly where the math needs it.
    """
    cfg = model.config
    from jax.sharding import NamedSharding

    if cfg.moe_experts:
        raise ValueError(
            "make_parallel_train_step is dense-only (its Megatron sharding "
            "splits w1/w2 over the model axis; MoE layers have no w1/w2) — "
            "train MoE configs with the single-device or sp steps, or run "
            "moe_ffn under expert parallelism directly"
        )
    tp = mesh.shape.get(model_axis, 1)
    if cfg.n_heads % tp:
        raise ValueError(f"n_heads {cfg.n_heads} must divide by tensor "
                         f"parallelism {tp}")
    if cfg.d_ff % tp or cfg.d_model % tp:
        raise ValueError("d_model and d_ff must divide by tensor parallelism")
    h_loc, hd = cfg.n_heads // tp, cfg.head_dim
    sp = mesh.shape.get(seq_axis, 1)
    if cfg.sp_attn == "a2a" and h_loc % sp:
        raise ValueError(
            f"sp_attn='a2a' needs per-TP-shard heads ({h_loc}) divisible by "
            f"the sequence axis ({sp})"
        )
    sp_attn_fn = a2a_attention if cfg.sp_attn == "a2a" else ring_attention
    specs = tp_param_specs(cfg.n_layers, model_axis)
    # PartitionSpec subclasses tuple, hence the is_leaf guard.
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )

    def shard_params(params: Dict[str, Any]) -> Dict[str, Any]:
        # device_put validates the tree structures match, so a param leaf
        # missing from tp_param_specs errors instead of mis-pairing.
        return jax.device_put(to_tp_params(params), shardings)

    def local_apply(p, tokens, offset):
        B, S = tokens.shape
        dtype = cfg.dtype
        x = (p["embed"][tokens] + p["pos"][offset + jnp.arange(S)]).astype(dtype)
        for layer in p["layers"]:
            xn = _norm(x, layer["ln1"].astype(dtype))
            to_heads = lambda t: t.reshape(B, S, h_loc, hd).transpose(0, 2, 1, 3)
            o = sp_attn_fn(
                to_heads(xn @ layer["wq"].astype(dtype)),
                to_heads(xn @ layer["wk"].astype(dtype)),
                to_heads(xn @ layer["wv"].astype(dtype)),
                axis_name=seq_axis, causal=True,
            )
            o = o.transpose(0, 2, 1, 3).reshape(B, S, h_loc * hd)
            # row-parallel out-projection: partial sums -> replicated x
            x = x + lax.psum(o @ layer["wo"].astype(dtype), model_axis)
            xn = _norm(x, layer["ln2"].astype(dtype))
            hidden = jax.nn.gelu(xn @ layer["w1"].astype(dtype))
            x = x + lax.psum(hidden @ layer["w2"].astype(dtype), model_axis)
        x = _norm(x, p["ln_f"].astype(dtype))
        return x.astype(jnp.float32) @ p["embed"].T

    def local_step(p, tokens, targets, mask):
        S_loc = tokens.shape[1]
        offset = lax.axis_index(seq_axis) * S_loc

        def loss_fn(p):
            logits = local_apply(p, tokens, offset)
            return _masked_ce(logits, targets, mask, (data_axis, seq_axis))

        loss, grads = jax.value_and_grad(loss_fn)(p)
        new_p = jax.tree.map(
            lambda w, g: w - learning_rate * g.astype(w.dtype), p, grads
        )
        return new_p, loss

    tok_spec = P(data_axis, seq_axis)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())  # see make_sp_train_step
    def step(tp_params, tokens):
        targets, mask = _lm_targets_and_mask(tokens)
        return jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, tok_spec, tok_spec, tok_spec),
            out_specs=(specs, P()),
        )(tp_params, tokens, targets, mask)

    return step, shard_params


def make_ep_train_step(
    model: TransformerLM,
    mesh,
    learning_rate: float = 0.1,
    data_axis: str = DATA_AXIS,
    donate: bool = True,
):
    """Expert+data-parallel train step for MoE configs: the batch shards
    over ``data_axis`` and the SAME axis carries expert parallelism — each
    shard owns ``moe_experts / shards`` experts (MoE params sharded on
    their leading expert dim) and token buckets move to their expert's
    device and back via ``all_to_all`` over ICI (models/moe.py). Dense
    layers and attention run data-parallel; non-expert params stay
    replicated with the gradient psum inserted by shard_map's typed
    autodiff. Returns ``(step, shard_params)``."""
    from jax.sharding import NamedSharding

    cfg = model.config
    ep = mesh.shape[data_axis]
    if not cfg.moe_experts:
        raise ValueError("make_ep_train_step needs an MoE config "
                         "(moe_experts > 0); use the dp/sp steps for dense")
    if cfg.moe_experts % ep:
        raise ValueError(f"moe_experts {cfg.moe_experts} must divide by the "
                         f"{data_axis} axis size {ep}")

    rep = NamedSharding(mesh, P())
    exp = NamedSharding(mesh, P(data_axis))

    def param_specs(params):
        """ONE spec tree drives both placement and the shard_map in/out
        specs — deriving them separately would let the two layouts drift."""
        specs = jax.tree.map(lambda _: P(), params)
        for spec_layer, layer in zip(specs["layers"], params["layers"]):
            if "moe" in layer:
                spec_layer["moe"]["w1"] = P(data_axis)
                spec_layer["moe"]["w2"] = P(data_axis)
        return specs

    def shard_params(params):
        shardings = jax.tree.map(
            lambda s: exp if s == P(data_axis) else rep, param_specs(params),
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.device_put(params, shardings)

    def local_step(params, tokens, targets, mask):
        def loss_fn(p):
            logits, aux = model._apply_with_aux(p, tokens,
                                                moe_axis=data_axis)
            loss = _masked_ce(logits, targets, mask, (data_axis,))
            return loss + cfg.moe_aux_weight * lax.pmean(aux, data_axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = jax.tree.map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), params, grads
        )
        return new, loss

    tok_spec = P(data_axis)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(params, tokens):
        targets, mask = _lm_targets_and_mask(tokens)
        specs = param_specs(params)
        return jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, tok_spec, tok_spec, tok_spec),
            out_specs=(specs, P()),
        )(params, tokens, targets, mask)

    return step, shard_params


def make_pp_train_step(
    model: TransformerLM,
    mesh,
    learning_rate: float = 0.1,
    num_microbatches: Optional[int] = None,
    stage_axis: str = "stage",
    donate: bool = True,
):
    """Pipeline-parallel train step: the LM's blocks split into S
    contiguous stages over ``mesh``'s ``stage`` axis (GPipe microbatching,
    parallel/pipeline.py); embed/positions/final-norm stay replicated and
    run outside the pipeline. Returns ``(step, shard_params)``:
    ``shard_params(params)`` converts an ordinary init tree into the
    stage-stacked, stage-sharded layout; ``step(pp_params, tokens) ->
    (new_pp_params, loss)`` is one jitted SPMD program whose inter-stage
    activation transfers are ppermutes riding ICI."""
    from jax.sharding import NamedSharding

    from harmony_tpu.parallel.pipeline import make_pipeline_fn

    cfg = model.config
    if cfg.moe_experts:
        raise ValueError(
            "make_pp_train_step needs homogeneous layers to stage-stack; "
            "MoE configs interleave two layer structures — use the sp/dp "
            "steps (or set moe_experts=0)"
        )
    S = mesh.shape[stage_axis]
    if cfg.n_layers % S:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible into "
                         f"{S} pipeline stages")
    lps = cfg.n_layers // S

    def stage_fn(stage_layers, x):
        # stage_layers leaves are [layers_per_stage, ...]: apply in order
        def body(x, layer):
            return model._block(x, layer, None)[0], None

        x, _ = lax.scan(body, x, stage_layers)
        return x

    pipe = make_pipeline_fn(stage_fn, mesh, axis_name=stage_axis,
                            num_microbatches=num_microbatches)

    def to_pp(params):
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *params["layers"])
        stages = jax.tree.map(
            lambda a: a.reshape(S, lps, *a.shape[1:]), stacked
        )
        return {"embed": params["embed"], "pos": params["pos"],
                "ln_f": params["ln_f"], "stages": stages}

    rep = NamedSharding(mesh, P())
    staged = NamedSharding(mesh, P(stage_axis))

    def shard_params(params):
        pp = to_pp(params)
        # one device_put over a sharding pytree: structure mismatches error
        # instead of silently mis-pairing leaves
        shardings = {
            "embed": rep, "pos": rep, "ln_f": rep,
            "stages": jax.tree.map(lambda _: staged, pp["stages"]),
        }
        return jax.device_put(pp, shardings)

    def loss_fn(pp, tokens):
        inp, targets = tokens[:, :-1], tokens[:, 1:]
        x = _embed_in(cfg, pp["embed"], pp["pos"], inp)
        h = pipe(pp["stages"], x)
        h = _norm(h, pp["ln_f"].astype(cfg.dtype))
        logits = h.astype(jnp.float32) @ pp["embed"].T  # weight-tied readout
        return _next_token_ce(logits, targets)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(pp, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(pp, tokens)
        new = jax.tree.map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), pp, grads
        )
        return new, loss

    return step, shard_params


def load_text_tokens(
    path: str, seq_len: int, num_seqs: int = 0, vocab_size: int = 256
) -> np.ndarray:
    """Real-file LM data: byte-level tokenization of a text file into a
    [num_seqs, seq_len] int32 matrix (the LM counterpart of the classic
    apps' file loaders — usable as a JobConfig ``data_fn`` with
    ``data_args={"path": ..., "seq_len": ...}``).

    Bytes >= vocab_size fold modulo (byte-level needs vocab_size 256; a
    smaller vocab still trains, just lossily). ``num_seqs=0`` takes every
    whole window the file provides."""
    if vocab_size < 2:
        raise ValueError("vocab_size must be >= 2")
    if seq_len < 2:  # a next-token example needs at least 2 tokens
        raise ValueError(f"seq_len must be >= 2, got {seq_len}")
    if num_seqs < 0:
        raise ValueError(f"num_seqs must be >= 0, got {num_seqs}")
    raw = np.fromfile(path, np.uint8)
    total = raw.shape[0] // seq_len
    if total == 0:
        raise ValueError(
            f"{path}: {raw.shape[0]} bytes cannot fill one {seq_len}-token "
            "sequence"
        )
    if num_seqs and total < num_seqs:
        raise ValueError(
            f"{path}: holds {total} windows of {seq_len}, wanted {num_seqs}"
        )
    n = num_seqs or total
    toks = raw[: n * seq_len].reshape(n, seq_len).astype(np.int32)
    return toks % vocab_size


def make_lm_data(
    num_seqs: int, seq_len: int, vocab_size: int, seed: int = 0
) -> np.ndarray:
    """Synthetic learnable corpus: orderly token walks with noise (next
    token is predictable from the current one ~80% of the time), so
    cross-entropy falls measurably within a few epochs."""
    rng = np.random.default_rng(seed)
    step = rng.integers(1, 7, size=(num_seqs, 1))
    start = rng.integers(0, vocab_size, size=(num_seqs, 1))
    walk = (start + step * np.arange(seq_len)[None, :]) % vocab_size
    noise = rng.integers(0, vocab_size, size=walk.shape)
    take_noise = rng.random(walk.shape) < 0.2
    return np.where(take_noise, noise, walk).astype(np.int32)


# ---------------------------------------------------------------------------
# Trainer SPI integration (LM in the elastic PS table)
# ---------------------------------------------------------------------------

from harmony_tpu.models.pytree_trainer import PyTreeTrainer  # noqa: E402


class TransformerTrainer(PyTreeTrainer):
    """Train the LM through the framework's elastic-table substrate (see
    PyTreeTrainer for the row layout and optimizer-state sections). Batch =
    [B, S] int32 token matrix."""

    default_table_id = "lm-model"
    config_cls = TransformerConfig

    def build_model(self, config: TransformerConfig) -> TransformerLM:
        return TransformerLM(config)

    def loss_on_batch(self, params, batch):
        tokens = batch[0] if isinstance(batch, (tuple, list)) else batch
        return self.model.loss(params, tokens)
