"""harmony_tpu.models — neural model families (beyond the reference's apps).

The reference ships classic PS workloads only (SURVEY.md §2.7); this package
adds the model families a TPU framework is actually judged on — starting
with a decoder-only transformer LM whose attention runs on the
harmony_tpu.ops kernels (flash single-chip, ring for sequence parallelism)
and whose parameters live in the same elastic DenseTable substrate as every
other app (so checkpointing, migration and multi-tenancy apply unchanged).
"""
from harmony_tpu.models.generate import make_generate_fn
from harmony_tpu.models.moe import MoEConfig, init_moe_params, moe_ffn
from harmony_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    TransformerTrainer,
    make_lm_data,
)
from harmony_tpu.models.pytree_trainer import PyTreeTrainer
from harmony_tpu.models.vit import ViT, ViTConfig, ViTTrainer

__all__ = [
    "MoEConfig",
    "TransformerConfig",
    "TransformerLM",
    "TransformerTrainer",
    "PyTreeTrainer",
    "ViT",
    "ViTConfig",
    "ViTTrainer",
    "init_moe_params",
    "make_generate_fn",
    "make_lm_data",
    "moe_ffn",
]
