"""Mixture-of-Experts FFN with expert parallelism (all_to_all routing).

Completes the parallelism set (dp/tp/sp/pp/**ep**). Switch-Transformer
style: top-1 router with bounded per-expert capacity; dispatch/combine are
one-hot einsums (MXU-friendly, static shapes); with expert parallelism the
expert dimension is sharded over a mesh axis and token buckets move to
their expert's device — and back — via ``lax.all_to_all`` over ICI.

Semantics:
  * capacity C per (expert, source shard) = ceil(T_local * capacity_factor
    / num_experts); tokens routed beyond capacity are DROPPED by dispatch
    (their combine weight is 0) — callers keep a residual connection so a
    dropped token passes through unchanged (standard Switch behavior).
  * aux load-balance loss (mean over experts of fraction_dispatched *
    mean_router_prob * E) encourages uniform routing.

``moe_ffn`` is pure and runs anywhere; pass ``axis_name`` when the expert
leading dim of the params is sharded over that mesh axis (inside shard_map).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.5

    def capacity(self, num_tokens: int) -> int:
        return max(1, -(-int(num_tokens * self.capacity_factor) // self.num_experts))


def init_moe_params(rng: jax.Array, cfg: MoEConfig) -> Dict[str, jnp.ndarray]:
    """Router + per-expert FFN weights (expert-stacked on the leading dim —
    shard that dim over the EP mesh axis)."""
    kr, k1, k2 = jax.random.split(rng, 3)
    E, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(kr, (d, E), jnp.float32) * (d ** -0.5),
        "w1": jax.random.normal(k1, (E, d, f), jnp.float32) * (d ** -0.5),
        "w2": jax.random.normal(k2, (E, f, d), jnp.float32) * (f ** -0.5),
    }


def _dispatch_combine(x, router, num_experts: int, capacity: int):
    """Top-1 routing tensors: dispatch [T, E, C] one-hot, combine = dispatch
    * router prob, plus the Switch aux loss."""
    T = x.shape[0]
    logits = x.astype(jnp.float32) @ router          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)  # [T, E]
    # Position of each token within its expert's bucket (stable by index).
    pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot             # [T, E]
    keep = (pos < capacity) * onehot
    pos_oh = jax.nn.one_hot(pos.sum(axis=-1), capacity, dtype=jnp.float32)
    dispatch = keep[:, :, None] * pos_oh[:, None, :]                 # [T,E,C]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * Σ_e (fraction of tokens to e) * (mean prob of e)
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_ffn(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,                      # [T, d] local tokens
    cfg: MoEConfig,
    axis_name: Optional[str] = None,     # EP axis (params expert-sharded)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (out [T, d], aux_loss). Without ``axis_name`` all experts are
    local; with it, params' leading expert dim holds E/S local experts and
    token buckets are exchanged with ``all_to_all``."""
    T, d = x.shape
    E = cfg.num_experts
    C = cfg.capacity(T)
    dispatch, combine, aux = _dispatch_combine(x, params["router"], E, C)
    xe = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))  # [E,C,d]

    if axis_name is None:
        w1, w2 = params["w1"], params["w2"]           # [E, d, f], [E, f, d]
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, w1))
        ye = jnp.einsum("ecf,efd->ecd", h, w2)        # [E, C, d]
    else:
        S = lax.psum(1, axis_name)
        E_loc = E // S
        # [E, C, d] -> exchange: each device keeps its E_loc experts but
        # receives every shard's buckets for them: [S*E_loc, C, d] ->
        # all_to_all splits the expert axis and concatenates source shards.
        xe = xe.reshape(S, E_loc, C, d)
        xe = lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)              # [S, E_loc, C, d] src-major
        xe = xe.transpose(1, 0, 2, 3).reshape(E_loc, S * C, d)
        w1, w2 = params["w1"], params["w2"]           # [E_loc, d, f]
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, w1))
        ye = jnp.einsum("ecf,efd->ecd", h, w2)        # [E_loc, S*C, d]
        ye = ye.reshape(E_loc, S, C, d).transpose(1, 0, 2, 3)  # [S, E_loc, C, d]
        ye = lax.all_to_all(ye, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        ye = ye.reshape(E, C, d)

    out = jnp.einsum("tec,ecd->td", combine, ye).astype(x.dtype)
    return out, aux
