"""Numeric primitives shared by the model families (LM, ViT)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: valid values for a model config's ``attn`` field
ATTN_CHOICES = ("auto", "flash", "blockwise")


def dense_init(key, shape):
    """1/sqrt(fan_in)-scaled normal init for a [fan_in, ...] weight."""
    return jax.random.normal(key, shape, jnp.float32) * shape[0] ** -0.5


def rms_norm(x, w):
    """RMSNorm (f32 statistics regardless of activation dtype)."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (xf * scale).astype(x.dtype) * w


def validate_attn(attn: str) -> str:
    if attn not in ATTN_CHOICES:
        raise ValueError(f"unknown attn {attn!r}; choose from {ATTN_CHOICES}")
    return attn


def flash_ok(seq: int, block: int | None = None) -> bool:
    """Can the Pallas flash kernel tile this sequence length with the
    caller's block size? Blocks clamp to min(block, seq), so any
    seq <= block tiles exactly; longer sequences need divisibility.
    ``block`` must match what the caller passes to flash_attention
    (default: the kernel's DEFAULT_BLOCK_Q)."""
    if block is None:
        from harmony_tpu.ops.attention import DEFAULT_BLOCK_Q

        block = DEFAULT_BLOCK_Q
    return seq % min(block, seq) == 0


def resolve_attn(attn: str, seq: int, block: int | None = None) -> str:
    """'auto' -> 'flash' on TPU when the kernel can tile, else 'blockwise'."""
    if attn != "auto":
        return attn
    from harmony_tpu.utils.platform import tpu_backend

    return "flash" if tpu_backend() and flash_ok(seq, block) else "blockwise"
