"""Vision Transformer classifier — the vision model family.

No reference counterpart (SURVEY.md §2.7 ships classic PS workloads only):
like the LM, this exists because a TPU framework is judged on model
coverage. Design shares the LM's conventions — functional params pytree,
bf16 activations with f32 norm statistics and logits, attention through
the framework kernels (Pallas flash on TPU when the token count tiles,
blockwise elsewhere), `make_train_step` producing a jitted
data-parallel SPMD step over a mesh.

Layout: images [B, H, W, C] -> non-overlapping patches -> linear embed +
learned positions + CLS token -> pre-norm encoder blocks (non-causal
attention) -> CLS readout head.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from harmony_tpu.models.common import (
    dense_init,
    resolve_attn,
    rms_norm,
    validate_attn,
)
from harmony_tpu.models.pytree_trainer import PyTreeTrainer
from harmony_tpu.ops import blockwise_attention, flash_attention
from harmony_tpu.parallel.mesh import DATA_AXIS


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    num_classes: int = 10
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 4
    d_ff: int = 512
    dtype: Any = jnp.float32      # bf16 on hardware
    attn: str = "auto"            # "auto" | "flash" | "blockwise"

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError("patch_size must divide image_size")
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        validate_attn(self.attn)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    @property
    def seq(self) -> int:
        return self.num_patches + 1  # + CLS


_norm = rms_norm


class ViT:
    def __init__(self, cfg: ViTConfig) -> None:
        self.cfg = cfg

    def init(self, key) -> Dict[str, Any]:
        cfg = self.cfg
        ks = jax.random.split(key, 4 + cfg.n_layers)
        d, f = cfg.d_model, cfg.d_ff

        layers = []
        for i in range(cfg.n_layers):
            lk = jax.random.split(ks[4 + i], 4)
            layers.append({
                "ln1": jnp.ones((d,), jnp.float32),
                "wqkv": dense_init(lk[0], (d, 3 * d)),
                "wo": dense_init(lk[1], (d, d)),
                "ln2": jnp.ones((d,), jnp.float32),
                "w1": dense_init(lk[2], (d, f)),
                "w2": dense_init(lk[3], (f, d)),
            })
        return {
            "embed": dense_init(ks[0], (cfg.patch_dim, d)),
            "pos": 0.02 * jax.random.normal(ks[1], (cfg.seq, d), jnp.float32),
            "cls": jnp.zeros((d,), jnp.float32),
            "ln_f": jnp.ones((d,), jnp.float32),
            "head": dense_init(ks[2], (d, cfg.num_classes)),
            "layers": layers,
        }

    def _patchify(self, images: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        B = images.shape[0]
        p, n = cfg.patch_size, cfg.image_size // cfg.patch_size
        x = images.reshape(B, n, p, n, p, cfg.channels)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, n * n, cfg.patch_dim)

    def _attend(self, q, k, v):
        attn = resolve_attn(self.cfg.attn, self.cfg.seq)
        fn = flash_attention if attn == "flash" else blockwise_attention
        return fn(q, k, v, causal=False)

    def apply(self, params, images: jnp.ndarray) -> jnp.ndarray:
        """images [B, H, W, C] -> logits [B, num_classes]."""
        cfg = self.cfg
        B = images.shape[0]
        x = self._patchify(images.astype(cfg.dtype))
        x = x @ params["embed"].astype(cfg.dtype)
        cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype),
                               (B, 1, cfg.d_model))
        x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(cfg.dtype)

        def to_heads(t):
            return t.reshape(B, cfg.seq, cfg.n_heads, -1).transpose(0, 2, 1, 3)

        for layer in params["layers"]:
            xn = _norm(x, layer["ln1"].astype(cfg.dtype))
            qkv = xn @ layer["wqkv"].astype(cfg.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            o = self._attend(to_heads(q), to_heads(k), to_heads(v))
            o = o.transpose(0, 2, 1, 3).reshape(B, cfg.seq, cfg.d_model)
            x = x + o @ layer["wo"].astype(cfg.dtype)
            xn = _norm(x, layer["ln2"].astype(cfg.dtype))
            x = x + jax.nn.gelu(xn @ layer["w1"].astype(cfg.dtype)) \
                @ layer["w2"].astype(cfg.dtype)
        x = _norm(x[:, 0], params["ln_f"].astype(cfg.dtype))  # CLS token
        return x.astype(jnp.float32) @ params["head"]          # f32 logits

    def loss(self, params, images, labels) -> jnp.ndarray:
        logits = self.apply(params, images)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

    def accuracy(self, params, images, labels) -> jnp.ndarray:
        logits = self.apply(params, images)
        return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def make_train_step(model: ViT, mesh=None, learning_rate: float = 0.1,
                    donate: bool = True):
    """Jitted SGD step ``(params, images, labels) -> (params, loss)``;
    with ``mesh``, the batch shards over the data axis (params replicated,
    XLA inserts the gradient all-reduce at the batch contraction).
    ``donate`` (default, matching the LM steps) reuses the params buffer —
    callers must not read the old tree after a step; pass False when
    comparing trajectories from a shared initial tree."""
    dn = (0,) if donate else ()

    def step(params, images, labels):
        loss, grads = jax.value_and_grad(model.loss)(params, images, labels)
        new = jax.tree.map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), params, grads
        )
        return new, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=dn)
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())

    def sharded(params, images, labels):
        images = jax.lax.with_sharding_constraint(images, batch_sh)
        labels = jax.lax.with_sharding_constraint(labels, batch_sh)
        return step(params, images, labels)

    return jax.jit(sharded, out_shardings=(rep, rep), donate_argnums=dn)


class ViTTrainer(PyTreeTrainer):
    """ViT through the framework's elastic-table substrate (see
    PyTreeTrainer for the row layout and optimizer-state sections). Batch =
    (images [B,H,W,C], labels [B])."""

    default_table_id = "vit-model"
    config_cls = ViTConfig

    def build_model(self, config: ViTConfig) -> "ViT":
        return ViT(config)

    def loss_on_batch(self, params, batch):
        images, labels = batch
        return self.model.loss(params, images, labels)

    def eval_metrics(self, params, batch):
        images, labels = batch
        return {
            "loss": self.model.loss(params, images, labels),
            "accuracy": self.model.accuracy(params, images, labels),
        }


def make_synthetic(
    n: int, cfg: Optional[ViTConfig] = None, seed: int = 0, **cfg_kwargs
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-separable synthetic images: each class gets a random template,
    samples are noisy copies. Accepts flat config kwargs (image_size, ...)
    so JSON-serialized job configs can parameterize it; unknown keys (and
    kwargs alongside an explicit cfg) raise — a typo'd override must not
    silently revert to defaults."""
    if cfg is not None and cfg_kwargs:
        raise TypeError("pass either cfg= or flat config kwargs, not both")
    if cfg is None:
        unknown = set(cfg_kwargs) - set(ViTConfig.__dataclass_fields__)
        if unknown:
            raise TypeError(f"unknown make_synthetic kwargs {sorted(unknown)}")
        cfg = ViTConfig(**cfg_kwargs)
    rng = np.random.default_rng(seed)
    templates = rng.standard_normal(
        (cfg.num_classes, cfg.image_size, cfg.image_size, cfg.channels)
    ).astype(np.float32)
    y = rng.integers(0, cfg.num_classes, n).astype(np.int32)
    x = templates[y] + 0.5 * rng.standard_normal(
        (n, cfg.image_size, cfg.image_size, cfg.channels)
    ).astype(np.float32)
    return x, y
