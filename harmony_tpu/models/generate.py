"""Incremental decoding for the transformer LM — KV-cache generation.

TPU-first inference: the cache is a STATIC [B, H, max_seq, head_dim]
buffer per layer (XLA wants fixed shapes), each step writes its keys/
values at the current position with `dynamic_update_slice` and attends
over the whole buffer under a position mask, and the generation loop is
one `lax.scan` — a single compiled program for the entire continuation,
no per-token host round-trips (which on a remote-attached chip would
cost a network RTT per token).

Decode is memory-bound (one query row), so attention here is a plain
masked softmax over the cache — the flash kernel's tiling buys nothing
at query length 1.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from harmony_tpu.models.common import rms_norm as _norm

_NEG_INF = -1e30


def init_kv_cache(cfg, batch: int) -> Dict[str, jnp.ndarray]:
    """Per-layer K/V buffers, stacked over layers: [L, B, H, max_seq, hd]."""
    shape = (cfg.n_layers, batch, cfg.n_heads, cfg.max_seq, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def decode_step(model, params, cache, token: jnp.ndarray, pos: jnp.ndarray):
    """One token for the whole batch: ``token`` [B] int32 at position
    ``pos`` (scalar int32). Returns (logits [B, vocab] f32, new cache)."""
    cfg = model.config
    B = token.shape[0]
    h, hd, d = cfg.n_heads, cfg.head_dim, cfg.d_model
    x = (params["embed"][token] + params["pos"][pos]).astype(cfg.dtype)  # [B,d]

    # mask over cache positions: attend to <= pos only
    valid = (jnp.arange(cfg.max_seq) <= pos)[None, None, :]      # [1,1,S]

    # The stacked cache buffers update IN PLACE (one position per layer per
    # step): under a scan carry XLA aliases the buffer, so per-token HBM
    # traffic is the attention reads plus one row write — NOT a rebuild of
    # the whole [L,B,H,S,hd] stack (slicing layers out and re-stacking
    # would copy the full cache every token and dominate the decode).
    cache_k, cache_v = cache["k"], cache["v"]
    for i, layer in enumerate(params["layers"]):
        xn = _norm(x, layer["ln1"].astype(cfg.dtype))
        qkv = xn @ layer["wqkv"].astype(cfg.dtype)               # [B, 3d]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, h, 1, hd)
        cache_k = lax.dynamic_update_slice(
            cache_k, k.reshape(1, B, h, 1, hd), (i, 0, 0, pos, 0))
        cache_v = lax.dynamic_update_slice(
            cache_v, v.reshape(1, B, h, 1, hd), (i, 0, 0, pos, 0))
        ck = cache_k[i]                                          # [B,h,S,hd]
        cv = cache_v[i]
        s = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) * (hd ** -0.5)    # [B,h,1,S]
        s = jnp.where(valid[None], s, _NEG_INF)
        o = jnp.einsum("bhqs,bhsd->bhqd", jax.nn.softmax(s, axis=-1),
                       cv.astype(jnp.float32)).astype(cfg.dtype)
        x = x + o.reshape(B, d) @ layer["wo"].astype(cfg.dtype)
        xn = _norm(x, layer["ln2"].astype(cfg.dtype))
        x = x + _ffn(cfg, layer, xn)
    xf = _norm(x, params["ln_f"].astype(cfg.dtype))
    logits = xf.astype(jnp.float32) @ params["embed"].T          # [B, vocab]
    return logits, {"k": cache_k, "v": cache_v}


def _ffn(cfg, layer, xn):
    """Decode-side FFN: the shared dense/MoE dispatch with NO-DROP expert
    capacity (per-step batches are tiny; the training capacity factor
    would drop tokens whenever two rows pick one expert)."""
    from harmony_tpu.models.transformer import ffn_apply

    return ffn_apply(cfg, layer, xn, no_drop=True)[0]


def prefill(model, params, cache, prompt: jnp.ndarray):
    """Fill the cache from the whole prompt in ONE batched causal forward
    (per-token prefill would cost prompt_len sequential 1-query dispatches
    at ~zero MXU utilization). Mirrors TransformerLM.apply's block math but
    writes every layer's K/V into the cache and returns the LAST position's
    logits — the state generation continues from."""
    cfg = model.config
    B, P = prompt.shape
    h, hd, d = cfg.n_heads, cfg.head_dim, cfg.d_model
    x = (params["embed"][prompt]
         + params["pos"][jnp.arange(P)]).astype(cfg.dtype)        # [B,P,d]
    q_pos = jnp.arange(P)[:, None]
    causal = (q_pos >= jnp.arange(P)[None, :])[None, None]        # [1,1,P,P]
    cache_k, cache_v = cache["k"], cache["v"]
    for i, layer in enumerate(params["layers"]):
        xn = _norm(x, layer["ln1"].astype(cfg.dtype))
        qkv = xn @ layer["wqkv"].astype(cfg.dtype)                # [B,P,3d]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, P, h, hd).transpose(0, 2, 1, 3)
        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        cache_k = lax.dynamic_update_slice(
            cache_k, kh[None], (i, 0, 0, 0, 0))
        cache_v = lax.dynamic_update_slice(
            cache_v, vh[None], (i, 0, 0, 0, 0))
        s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                       kh.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(causal, s, _NEG_INF)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                       vh.astype(jnp.float32)).astype(cfg.dtype)
        x = x + o.transpose(0, 2, 1, 3).reshape(B, P, d) \
            @ layer["wo"].astype(cfg.dtype)
        xn = _norm(x, layer["ln2"].astype(cfg.dtype))
        x = x + _ffn(cfg, layer, xn)
    xf = _norm(x[:, -1], params["ln_f"].astype(cfg.dtype))
    logits = xf.astype(jnp.float32) @ params["embed"].T           # [B,V]
    return logits, {"k": cache_k, "v": cache_v}


def make_generate_fn(model, prompt_len: int, num_new: int,
                     temperature: float = 0.0):
    """Build a jitted ``generate(params, prompt [B, prompt_len], key) ->
    tokens [B, prompt_len + num_new]``.

    One compiled program: a single batched prefill forward fills the cache
    from the prompt, then a decode scan samples ``num_new`` tokens (greedy
    at temperature 0). ``prompt_len + num_new`` must fit
    ``config.max_seq``."""
    cfg = model.config
    total = prompt_len + num_new
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt_len + num_new = {total} exceeds max_seq {cfg.max_seq}"
        )

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits / temperature, axis=-1
        ).astype(jnp.int32)

    def generate(params, prompt, key: Optional[jax.Array] = None):
        B = prompt.shape[0]
        if key is None:
            key = jax.random.PRNGKey(0)
        cache = init_kv_cache(cfg, B)
        logits, cache = prefill(model, params, cache,
                                prompt.astype(jnp.int32))

        def decode(carry, step_key):
            cache, logits, pos = carry
            tok = pick(logits, step_key)
            new_logits, cache = decode_step(model, params, cache, tok, pos)
            return (cache, new_logits, pos + 1), tok

        keys = jax.random.split(key, num_new)
        (_, _, _), out = lax.scan(
            decode, (cache, logits, jnp.int32(prompt_len)), keys
        )
        return jnp.concatenate([prompt.astype(jnp.int32), out.T], axis=1)

    return jax.jit(generate)
