"""DataParser SPI + built-in text formats.

Parity with the reference's per-app parsers (SURVEY.md §2.1 BulkDataLoader +
DataParser; each mlapp ships an ``<App>ETDataParser``): a parser turns a
split's raw records into typed arrays ready for table/bulk insertion.

Built-ins cover the reference's app data shapes:
  * ``LibSvmParser``   — "label idx:val idx:val …" (MLR/Lasso/GBT-style
    labeled sparse rows -> dense features + label);
  * ``CsvParser``      — plain numeric rows;
  * ``KeyValueVectorParser`` — "key v0 v1 v2 …" rows (NMF-style keyed rows).

Parsers are registered by name so a serialized TableConfig can carry
``parser="libsvm"`` across process boundaries (the Tang-binding analogue).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple, Type

import numpy as np


class DataParser:
    """SPI: records -> arrays (ref: evaluator/api/DataParser)."""

    def parse(self, records: Sequence[str]):
        raise NotImplementedError


_REGISTRY: Dict[str, Type[DataParser]] = {}


def register_parser(name: str):
    def deco(cls: Type[DataParser]):
        _REGISTRY[name] = cls
        return cls

    return deco


def get_parser(name: str, **kwargs) -> DataParser:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown parser {name!r}; registered: {sorted(_REGISTRY)}") from None
    return cls(**kwargs)


@register_parser("libsvm")
class LibSvmParser(DataParser):
    """label idx:value ... -> (x [N, num_features] float32, y [N] float32).

    Indices are ``base``-based (libsvm files are traditionally 1-based)."""

    def __init__(self, num_features: int, base: int = 1) -> None:
        self.num_features = num_features
        self.base = base

    def parse(self, records: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        from harmony_tpu import native

        if native.available() and records:
            # C++ hot loop (native/harmony_native.cc: ht_parse_libsvm).
            x, y = native.parse_libsvm(
                "\n".join(records) + "\n", self.num_features, self.base
            )
            if x.shape[0] == len(records):
                return x, y
            # Row-count drift (e.g. records containing embedded newlines):
            # fall through to the reference Python path.
        n = len(records)
        x = np.zeros((n, self.num_features), np.float32)
        y = np.zeros((n,), np.float32)
        for i, rec in enumerate(records):
            parts = rec.split()
            y[i] = float(parts[0])
            for tok in parts[1:]:
                idx, val = tok.split(":")
                j = int(idx) - self.base
                if 0 <= j < self.num_features:
                    x[i, j] = float(val)
        return x, y


@register_parser("csv")
class CsvParser(DataParser):
    """Numeric CSV rows -> one float32 matrix (label column optional)."""

    def __init__(self, delimiter: str = ",", label_col: int | None = None) -> None:
        self.delimiter = delimiter
        self.label_col = label_col

    def parse(self, records: Sequence[str]):
        rows = [
            [float(v) for v in rec.split(self.delimiter)] for rec in records
        ]
        mat = np.asarray(rows, np.float32) if rows else np.zeros((0, 0), np.float32)
        if self.label_col is None:
            return mat
        y = mat[:, self.label_col]
        x = np.delete(mat, self.label_col, axis=1)
        return x, y


@register_parser("keyvec")
class KeyValueVectorParser(DataParser):
    """"key v0 v1 ..." rows -> (keys [N] int32, values [N, D] float32)
    (ref: NMF-style keyed row input; keys feed ExistKeyBulkDataLoader
    semantics — the key comes from the data, not a generator)."""

    def parse(self, records: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        keys: List[int] = []
        vals: List[List[float]] = []
        for rec in records:
            parts = rec.split()
            keys.append(int(parts[0]))
            vals.append([float(v) for v in parts[1:]])
        return (
            np.asarray(keys, np.int32),
            np.asarray(vals, np.float32) if vals else np.zeros((0, 0), np.float32),
        )
