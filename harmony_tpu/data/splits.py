"""File splits — exactly-N split computation decoupled from loading.

Parity with the reference's dataloader (SURVEY.md §2.9): HDFS split
computation is done ONCE on the driver (`HdfsSplitManager`), serialized as
`HdfsSplitInfo`, and each executor fetches only its splits
(`HdfsSplitFetcher.fetchData`, common/.../dataloader/HdfsSplitFetcher.java:
31-45). `ExactNumSplitFileInputFormat` (332 LoC) forces EXACTLY N splits so
the number of partitions matches the number of workers regardless of file
block layout.

Rebuilt for posix/GCS-style storage: the file set is treated as one virtual
byte concatenation carved into exactly N contiguous ranges; a range maps to
one or more per-file pieces (so N < number-of-files still covers every file
— a split simply spans files). Text-record alignment follows the Hadoop
LineRecordReader contract per piece: a reader at in-file offset>0 drops
through the first newline (reading from offset-1, so a boundary exactly at a
record start drops nothing) and reads past its end to finish its last
record. Records never span files, so every record lands in exactly one
split.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from harmony_tpu.config.base import ConfigBase, config


@config
class SplitInfo(ConfigBase):
    """One split: a list of ``(path, offset, length)`` pieces (serializable —
    driver computes, executor fetches; ref: HdfsSplitInfoSerializer)."""

    pieces: List[Tuple[str, int, int]]
    split_idx: int = 0
    num_splits: int = 1


def compute_splits(paths: Sequence[str], num_splits: int) -> List[SplitInfo]:
    """Exactly ``num_splits`` splits over the concatenation of ``paths``
    (ref: ExactNumSplitFileInputFormat semantics). Every byte of every file
    is covered exactly once; zero-length splits appear when there are more
    splits than bytes (fetch returns empty, matching the reference's
    tolerance of empty partitions)."""
    import os

    if num_splits <= 0:
        raise ValueError("num_splits must be positive")
    sizes = [(p, os.path.getsize(p)) for p in paths]
    total = sum(s for _, s in sizes)
    base, extra = divmod(total, num_splits)
    # Virtual-range boundaries: first `extra` splits get base+1 bytes.
    splits: List[SplitInfo] = []
    file_idx, file_off = 0, 0
    for i in range(num_splits):
        want = base + (1 if i < extra else 0)
        pieces: List[Tuple[str, int, int]] = []
        while want > 0:
            path, size = sizes[file_idx]
            take = min(want, size - file_off)
            if take > 0:
                pieces.append((path, file_off, take))
                file_off += take
                want -= take
            if file_off >= size:
                file_idx += 1
                file_off = 0
                if file_idx >= len(sizes):
                    break
        splits.append(SplitInfo(pieces=pieces, split_idx=i, num_splits=num_splits))
    return splits


def _fetch_range(path: str, offset: int, length: int) -> List[str]:
    """Complete text records of one in-file byte range (LineRecordReader
    alignment: drop-through-first-newline from offset-1, read past end to
    finish the last record)."""
    if length <= 0:
        return []
    with open(path, "rb") as f:
        if offset > 0:
            f.seek(offset - 1)
            chunk = f.read(length + 1)
            nl = chunk.find(b"\n")
            if nl < 0:
                return []  # entire range is mid-record: owned by predecessor
            chunk = chunk[nl + 1 :]
            if not chunk:
                # No record STARTS inside this range (records belong to the
                # split containing their first byte) — nothing to read.
                return []
        else:
            chunk = f.read(length)
        # Finish our last record by reading past the range end.
        if not chunk.endswith(b"\n"):
            while True:
                b = f.read(4096)
                if not b:
                    break
                nl = b.find(b"\n")
                if nl >= 0:
                    chunk += b[: nl + 1]
                    break
                chunk += b
    return [ln for ln in chunk.decode("utf-8").split("\n") if ln.strip()]


def fetch_split(split: SplitInfo) -> List[str]:
    """Read one split's complete text records (ref: HdfsSplitFetcher.fetchData
    returning the split's raw records for the DataParser)."""
    out: List[str] = []
    for path, offset, length in split.pieces:
        out.extend(_fetch_range(path, int(offset), int(length)))
    return out
