"""PrefetchLoader — ordered background split reading.

The reference's bulk load overlaps network fetch with table insertion via
the HDFS client's own threads (TableLoadMsg → BulkDataLoader →
HdfsSplitFetcher, SURVEY.md §3.2); the training loop itself reads nothing.
Here file-fed jobs DO stream splits, so the loader is a real runtime
component: a C++ worker pool (native/harmony_native.cc ht_prefetch_*)
reads split byte-ranges with bounded lookahead and delivers them in
submission order, keeping epoch composition deterministic while IO
overlaps parsing/compute. A pure-Python thread pool provides the same
contract when the native library is unavailable.
"""
from __future__ import annotations

import ctypes
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterator, List, Optional, Sequence

from harmony_tpu.data.splits import SplitInfo, fetch_split


def _decode(raw: bytes) -> List[str]:
    return [ln for ln in raw.decode("utf-8").split("\n") if ln.strip()]


class StageRing:
    """Bounded single-producer/single-consumer staging ring — the host-side
    backbone for input pipelines. Today its one consumer is the training
    input prefetcher (dolphin/prefetch stages device batches through it);
    it lives here, beside PrefetchLoader, as the shared primitive for any
    future ordered produce/consume stage (PrefetchLoader itself still uses
    its thread-pool lookahead, which additionally fetches splits in
    parallel).

    ``cap_fn`` is re-evaluated on every put so the depth can track a live
    signal (the worker's in-flight cap: shallow under TaskUnit contention,
    deep otherwise); a cap decrease applies to new puts while already-staged
    items drain normally. ``close()`` (consumer side) unblocks the producer
    — its next put returns False — and drops staged items; a producer-side
    exception recorded with ``set_error`` re-raises at the consumer's get()
    AFTER the staged prefix drains, mirroring how an in-line iterator would
    fail mid-epoch.

    Counters (read after the run): ``producer_idle_sec`` — producer time
    blocked on a full ring (the pipeline outran the consumer: good),
    ``consumer_stall_sec`` — consumer time blocked on an empty ring (the
    pipeline is the bottleneck: bad), ``max_depth`` — high-water mark,
    ``staged`` — total items that entered the ring.
    """

    DONE = object()  # returned by get() once the producer is done/closed

    def __init__(self, cap_fn: Callable[[], int]) -> None:
        self._cap_fn = cap_fn
        self._cond = threading.Condition()
        self._items: deque = deque()
        self._closed = False
        self._finished = False
        self._error: Optional[BaseException] = None
        self.producer_idle_sec = 0.0
        self.consumer_stall_sec = 0.0
        self.max_depth = 0
        self.staged = 0

    def _space(self) -> bool:
        return self._closed or len(self._items) < max(1, int(self._cap_fn()))

    def put(self, item: Any) -> bool:
        """Stage one item; blocks while the ring is at its cap. Returns
        False once the consumer closed the ring (stop producing)."""
        with self._cond:
            if not self._space():
                t0 = time.perf_counter()
                self._cond.wait_for(self._space)
                self.producer_idle_sec += time.perf_counter() - t0
            if self._closed:
                return False
            self._items.append(item)
            self.staged += 1
            self.max_depth = max(self.max_depth, len(self._items))
            self._cond.notify_all()
            return True

    def get(self) -> Any:
        """Next staged item, ``StageRing.DONE`` at end-of-stream, or the
        producer's exception re-raised (after staged items drained)."""
        with self._cond:
            if not self._items and not (self._finished or self._closed):
                t0 = time.perf_counter()
                self._cond.wait_for(
                    lambda: self._items or self._finished or self._closed
                )
                self.consumer_stall_sec += time.perf_counter() - t0
            if self._items:
                item = self._items.popleft()
                self._cond.notify_all()
                return item
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return self.DONE

    def finish(self) -> None:
        """Producer side: end-of-stream."""
        with self._cond:
            self._finished = True
            self._cond.notify_all()

    def set_error(self, exc: BaseException) -> None:
        """Producer side: record a failure for the consumer to re-raise."""
        with self._cond:
            self._error = exc
            self._finished = True
            self._cond.notify_all()

    def close(self) -> None:
        """Consumer side: abort the stream (early stop / worker teardown)."""
        with self._cond:
            self._closed = True
            self._items.clear()
            self._cond.notify_all()

    def apply(self, fn: Callable[[Any], None]) -> int:
        """Run ``fn`` over every staged item under the lock (reshard
        invalidation mutates staged entries in place); returns the count."""
        with self._cond:
            for item in self._items:
                fn(item)
            return len(self._items)

    def depth(self) -> int:
        with self._cond:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed


class PrefetchLoader:
    """Iterate split record-lists in order while later splits load in the
    background. SINGLE PASS: the loader is an exhaustible stream (the
    native cursor only moves forward), so a second iteration raises
    instead of silently differing between the native and fallback paths.
    Use as a context manager (or call :meth:`close`)."""

    def __init__(
        self,
        splits: Sequence[SplitInfo],
        depth: int = 2,
        workers: int = 2,
        force_python: bool = False,
    ) -> None:
        if depth < 1 or workers < 1:
            raise ValueError("depth and workers must be >= 1")
        self.splits = list(splits)
        self.depth = depth
        self.workers = workers
        self._handle = None
        self._lib = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._consumed = False
        if not force_python:
            self._open_native()

    # -- native path ------------------------------------------------------

    def _open_native(self) -> None:
        from harmony_tpu import native

        if not native.available():
            return
        lib = native._load()
        flat = [(p, int(o), int(n)) for s in self.splits for (p, o, n) in s.pieces]
        n = len(flat)
        paths = (ctypes.c_char_p * n)(*[p.encode() for p, _, _ in flat])
        offsets = (ctypes.c_uint64 * n)(*[o for _, o, _ in flat])
        lengths = (ctypes.c_uint64 * n)(*[ln for _, _, ln in flat])
        counts = (ctypes.c_int32 * len(self.splits))(
            *[len(s.pieces) for s in self.splits]
        )
        handle = lib.ht_prefetch_open(
            paths, offsets, lengths, counts,
            len(self.splits), self.depth, self.workers,
        )
        if handle:
            # keep the ctypes arrays alive for the handle's lifetime
            self._keep = (paths, offsets, lengths, counts)
            self._handle = handle
            self._lib = lib

    def _iter_native(self) -> Iterator[List[str]]:
        lib = self._lib
        for idx in range(len(self.splits)):
            out = ctypes.POINTER(ctypes.c_uint8)()
            size = lib.ht_prefetch_next(self._handle, ctypes.byref(out))
            if size == -1:
                return
            if size < 0:
                raise IOError(
                    f"prefetch read failed on split {idx} "
                    f"({self.splits[idx].pieces})"
                )
            try:
                raw = ctypes.string_at(out, size)
            finally:
                lib.ht_prefetch_buf_free(out)
            yield _decode(raw)

    # -- python fallback --------------------------------------------------

    def _iter_python(self) -> Iterator[List[str]]:
        self._pool = ThreadPoolExecutor(max_workers=self.workers)
        futures = {}
        try:
            for idx in range(min(self.depth, len(self.splits))):
                futures[idx] = self._pool.submit(fetch_split, self.splits[idx])
            for idx in range(len(self.splits)):
                nxt = idx + self.depth
                if nxt < len(self.splits):
                    futures[nxt] = self._pool.submit(fetch_split, self.splits[nxt])
                yield futures.pop(idx).result()
        finally:
            self.close()

    def __iter__(self) -> Iterator[List[str]]:
        if self._consumed:
            raise RuntimeError(
                "PrefetchLoader is single-pass; construct a new one to re-read"
            )
        self._consumed = True
        if self._handle is not None:
            return self._iter_native()
        return self._iter_python()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.ht_prefetch_close(self._handle)
            self._handle = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # safety net; explicit close preferred
        try:
            self.close()
        except Exception:
            pass
