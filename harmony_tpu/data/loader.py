"""PrefetchLoader — ordered background split reading.

The reference's bulk load overlaps network fetch with table insertion via
the HDFS client's own threads (TableLoadMsg → BulkDataLoader →
HdfsSplitFetcher, SURVEY.md §3.2); the training loop itself reads nothing.
Here file-fed jobs DO stream splits, so the loader is a real runtime
component: a C++ worker pool (native/harmony_native.cc ht_prefetch_*)
reads split byte-ranges with bounded lookahead and delivers them in
submission order, keeping epoch composition deterministic while IO
overlaps parsing/compute. A pure-Python thread pool provides the same
contract when the native library is unavailable.
"""
from __future__ import annotations

import ctypes
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, List, Optional, Sequence

from harmony_tpu.data.splits import SplitInfo, fetch_split


def _decode(raw: bytes) -> List[str]:
    return [ln for ln in raw.decode("utf-8").split("\n") if ln.strip()]


class PrefetchLoader:
    """Iterate split record-lists in order while later splits load in the
    background. SINGLE PASS: the loader is an exhaustible stream (the
    native cursor only moves forward), so a second iteration raises
    instead of silently differing between the native and fallback paths.
    Use as a context manager (or call :meth:`close`)."""

    def __init__(
        self,
        splits: Sequence[SplitInfo],
        depth: int = 2,
        workers: int = 2,
        force_python: bool = False,
    ) -> None:
        if depth < 1 or workers < 1:
            raise ValueError("depth and workers must be >= 1")
        self.splits = list(splits)
        self.depth = depth
        self.workers = workers
        self._handle = None
        self._lib = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._consumed = False
        if not force_python:
            self._open_native()

    # -- native path ------------------------------------------------------

    def _open_native(self) -> None:
        from harmony_tpu import native

        if not native.available():
            return
        lib = native._load()
        flat = [(p, int(o), int(n)) for s in self.splits for (p, o, n) in s.pieces]
        n = len(flat)
        paths = (ctypes.c_char_p * n)(*[p.encode() for p, _, _ in flat])
        offsets = (ctypes.c_uint64 * n)(*[o for _, o, _ in flat])
        lengths = (ctypes.c_uint64 * n)(*[ln for _, _, ln in flat])
        counts = (ctypes.c_int32 * len(self.splits))(
            *[len(s.pieces) for s in self.splits]
        )
        handle = lib.ht_prefetch_open(
            paths, offsets, lengths, counts,
            len(self.splits), self.depth, self.workers,
        )
        if handle:
            # keep the ctypes arrays alive for the handle's lifetime
            self._keep = (paths, offsets, lengths, counts)
            self._handle = handle
            self._lib = lib

    def _iter_native(self) -> Iterator[List[str]]:
        lib = self._lib
        for idx in range(len(self.splits)):
            out = ctypes.POINTER(ctypes.c_uint8)()
            size = lib.ht_prefetch_next(self._handle, ctypes.byref(out))
            if size == -1:
                return
            if size < 0:
                raise IOError(
                    f"prefetch read failed on split {idx} "
                    f"({self.splits[idx].pieces})"
                )
            try:
                raw = ctypes.string_at(out, size)
            finally:
                lib.ht_prefetch_buf_free(out)
            yield _decode(raw)

    # -- python fallback --------------------------------------------------

    def _iter_python(self) -> Iterator[List[str]]:
        self._pool = ThreadPoolExecutor(max_workers=self.workers)
        futures = {}
        try:
            for idx in range(min(self.depth, len(self.splits))):
                futures[idx] = self._pool.submit(fetch_split, self.splits[idx])
            for idx in range(len(self.splits)):
                nxt = idx + self.depth
                if nxt < len(self.splits):
                    futures[nxt] = self._pool.submit(fetch_split, self.splits[nxt])
                yield futures.pop(idx).result()
        finally:
            self.close()

    def __iter__(self) -> Iterator[List[str]]:
        if self._consumed:
            raise RuntimeError(
                "PrefetchLoader is single-pass; construct a new one to re-read"
            )
        self._consumed = True
        if self._handle is not None:
            return self._iter_native()
        return self._iter_python()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.ht_prefetch_close(self._handle)
            self._handle = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # safety net; explicit close preferred
        try:
            self.close()
        except Exception:
            pass
