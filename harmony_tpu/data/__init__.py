from harmony_tpu.data.splits import SplitInfo, compute_splits, fetch_split
from harmony_tpu.data.parsers import (
    DataParser,
    CsvParser,
    LibSvmParser,
    KeyValueVectorParser,
    get_parser,
    register_parser,
)
from harmony_tpu.data.loader import PrefetchLoader
from harmony_tpu.data.storer import DataStorer, FileDataStorer


def load_dataset(paths, parser, num_splits: int = 1):
    """Worker-side input path: fetch+parse all splits and concatenate into
    the arrays TrainingDataProvider consumes (the reference's input-table
    bulk load collapsed to host arrays — SPMD workers shard per step)."""
    import numpy as np

    parts = []
    with PrefetchLoader(compute_splits(list(paths), num_splits)) as loader:
        for records in loader:
            if records:
                parts.append(parser.parse(records))
    if not parts:
        raise ValueError(f"no records in {paths}")
    first = parts[0]
    if isinstance(first, tuple):
        return tuple(np.concatenate([p[i] for p in parts]) for i in range(len(first)))
    return np.concatenate(parts)

__all__ = [
    "SplitInfo",
    "compute_splits",
    "fetch_split",
    "PrefetchLoader",
    "DataParser",
    "CsvParser",
    "LibSvmParser",
    "KeyValueVectorParser",
    "get_parser",
    "register_parser",
    "DataStorer",
    "FileDataStorer",
]
