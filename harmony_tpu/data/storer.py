"""DataStorer — write job results to durable storage.

Parity with the reference's datastorer (SURVEY.md §2.9: ``DataStorer`` SPI +
``HdfsDataStorer``, common/.../datastorer/, 195 LoC): trainers/apps persist
final models or outputs to a durable path at job end. The durable target
here is a posix directory (a GCS bucket mounts the same way on TPU VMs).
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict

import numpy as np


class DataStorer:
    def store_array(self, rel_path: str, arr: np.ndarray) -> str:
        raise NotImplementedError

    def store_json(self, rel_path: str, obj: Dict[str, Any]) -> str:
        raise NotImplementedError

    def store_text(self, rel_path: str, text: str) -> str:
        raise NotImplementedError


class FileDataStorer(DataStorer):
    """Atomic writes into a root directory: temp file + rename, so readers
    (and a crash) never observe partial results — the posix analogue of the
    HDFS create-then-close visibility the reference relies on."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _target(self, rel_path: str) -> str:
        path = os.path.join(self.root, rel_path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return path

    def _atomic_write(self, rel_path: str, write_fn) -> str:
        path = self._target(rel_path)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                write_fn(f)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return path

    def store_array(self, rel_path: str, arr: np.ndarray) -> str:
        return self._atomic_write(rel_path, lambda f: np.save(f, arr))

    def store_json(self, rel_path: str, obj: Dict[str, Any]) -> str:
        return self._atomic_write(rel_path, lambda f: f.write(json.dumps(obj, indent=2).encode()))

    def store_text(self, rel_path: str, text: str) -> str:
        return self._atomic_write(rel_path, lambda f: f.write(text.encode()))

    def load_array(self, rel_path: str) -> np.ndarray:
        return np.load(os.path.join(self.root, rel_path))
