"""Process-level caches of materialized input data.

The reference deliberately SHARES input tables across jobs with the same
table id (DolphinJobEntity.java:76-121: "reuses existing input table across
jobs if id matches") — loading the training set once and letting every
subsequent job of the same app read it. In this framework input data is not
a table (it feeds jitted steps directly), so the analogue is two caches
keyed by the DATA SOURCE identity (generator/loader dotted path + args):

  * a host-array cache (the job entity's ``_make_data``), so resubmitting
    a job does not regenerate/reload 100s of MB, and so every job with the
    same source sees the SAME dataset by definition;
  * this module's byte-bounded device cache of per-batch/stacked device
    arrays, so the host->device transfer happens once — on a
    remote-attached chip that transfer is seconds per submission.

Cached device arrays are read-only by contract: training steps never donate
batch arguments (only the table state), so a cached buffer is never
invalidated by a step.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional, Tuple


class ByteLRU:
    """Thread-safe LRU bounded by the total byte size of its values."""

    def __init__(self, max_bytes: int) -> None:
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Hashable, Tuple[Any, int]]" = OrderedDict()
        self._bytes = 0
        self.max_bytes = max_bytes
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _nbytes(value: Any) -> int:
        leaves = value if isinstance(value, (tuple, list)) else (value,)
        return sum(int(getattr(a, "nbytes", 0)) for a in leaves)

    def get(self, key: Optional[Hashable]):
        if key is None:
            return None
        with self._lock:
            hit = self._cache.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._cache.move_to_end(key)
            self.hits += 1
            return hit[0]

    def contains(self, key: Optional[Hashable]) -> bool:
        """Presence probe that perturbs NEITHER the LRU order nor the
        hit/miss counters — planning queries (e.g. "can this epoch bypass
        host work?") must not masquerade as cache traffic."""
        if key is None:
            return False
        with self._lock:
            return key in self._cache

    def put(self, key: Optional[Hashable], value: Any) -> None:
        if key is None:
            return
        nb = self._nbytes(value)
        if nb > self.max_bytes:
            return  # larger than the whole budget: never cacheable
        with self._lock:
            old = self._cache.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._cache[key] = (value, nb)
            self._bytes += nb
            while self._bytes > self.max_bytes and self._cache:
                _, (_, evicted) = self._cache.popitem(last=False)
                self._bytes -= evicted

    def drop(self, predicate) -> int:
        """Remove every entry whose key matches; returns the count. Used to
        release device buffers made unreachable by a live reshard (their
        keys embed the old sharding signature and can never hit again)."""
        with self._lock:
            stale = [k for k in self._cache if predicate(k)]
            for k in stale:
                _, nb = self._cache.pop(k)
                self._bytes -= nb
            return len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "bytes": self._bytes, "entries": len(self._cache)}

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
            self._bytes = 0
            self.hits = self.misses = 0


# Device-resident batches: bounded well below any chip's HBM; raise via
# set_max_bytes for hosts that want more residency.
_device = ByteLRU(2 << 30)
# Host arrays (the entity's dataset cache): host RAM is cheaper.
host_data = ByteLRU(4 << 30)


def get(key: Optional[Hashable]):
    return _device.get(key)


def contains(key: Optional[Hashable]) -> bool:
    return _device.contains(key)


def put(key: Optional[Hashable], value: Any) -> None:
    _device.put(key, value)


def set_max_bytes(n: int) -> None:
    _device.max_bytes = int(n)


def drop(predicate) -> int:
    return _device.drop(predicate)


def stats() -> dict:
    return _device.stats()


def clear() -> None:
    _device.clear()
