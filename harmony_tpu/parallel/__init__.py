from harmony_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    DevicePool,
    build_mesh,
    local_devices,
)

__all__ = ["DATA_AXIS", "MODEL_AXIS", "DevicePool", "build_mesh", "local_devices"]
