"""Process-wide serializer for multi-device program dispatch.

XLA's in-process collectives (the CPU backend's InProcessCommunicator)
rendezvous per collective op across all participating devices, with each
participant needing a live execution thread. Two concurrent multi-device
programs can therefore kill the process two ways:

  * enqueue-order inversion — job A enqueued first on device 0, job B
    first on device 1: every device waits inside a different program's
    collective;
  * participant starvation — overlapping executions need more concurrent
    participant threads than the host has (reproduced on a 1-core host:
    three 8-device table steps in flight, rendezvous aborts the process
    after its termination timeout, rendezvous.cc "Exiting to ensure a
    consistent program state").

The remedy is the insight the reference encodes as its
GlobalTaskUnitScheduler (driver/impl/GlobalTaskUnitScheduler.java:29-36):
concurrent jobs sharing executors need ONE GLOBAL ORDER of work units.
There it removed per-executor divergence for fairness; here it is a
correctness requirement. Every multi-device dispatch in the framework
enters this scope:

  * all backends: programs ENQUEUE atomically across their devices in one
    process-wide order (fixes inversion; the lock is held microseconds);
  * in-process-collective backends (cpu): the caller additionally BLOCKS
    on the program inside the scope via the yielded ``finish`` hook, so at
    most one multi-device program executes at a time (fixes starvation).
    Real TPU queues execute in enqueue order with hardware collectives —
    ``finish`` is the identity there and dispatch stays asynchronous.

Single-device programs (no collectives, nothing to invert) skip the scope
entirely — the flagship single-chip path pays nothing.

Lock order convention: table lock(s) first, THEN this scope, and no other
lock is ever taken inside it.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_LOCK = threading.RLock()


def _identity(x):
    return x


def _mesh_info(mesh) -> "tuple[int, str]":
    devs = mesh.devices.flat
    first = next(iter(devs))
    return mesh.devices.size, first.platform


@contextlib.contextmanager
def dispatch_scope(mesh):
    """Enter the global enqueue-order scope for a program over ``mesh``.

    Yields a ``finish`` hook the caller passes its dispatched outputs
    through BEFORE leaving the scope: on in-process-collective backends it
    blocks until ready (serializing execution), elsewhere it is the
    identity (dispatch stays async).

        with dispatch_scope(table.mesh) as finish:
            out = finish(step(arr, batch))
    """
    n, platform = _mesh_info(mesh)
    if n <= 1:
        yield _identity
        return
    with _LOCK:
        if platform == "cpu":
            yield jax.block_until_ready
        else:
            yield _identity
