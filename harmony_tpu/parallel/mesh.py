"""Device mesh construction and carving.

This is the TPU analogue of the reference's resource layer: where Harmony
acquires a pool of N homogeneous REEF evaluators once at startup and shares
them among all jobs (ref: jobserver/driver/ResourcePool.java:39-106,
services/evalmanager/api/EvaluatorManager.java:39-73), the TPU build owns the
pod's device list and hands out *mesh slices* to jobs. An "executor" maps to
one device plus its host-side runtime state.

Axis convention:
  * ``data``  — batch (data-parallel) axis; gradients are summed across it.
  * ``model`` — table-shard axis; table blocks live along it (the analogue of
    block->server-executor placement, BlockManager.java:30-40).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def local_devices(n: Optional[int] = None) -> List[jax.Device]:
    """First ``n`` JAX devices (all if n is None)."""
    devs = list(jax.devices())
    if n is not None:
        if n > len(devs):
            raise ValueError(f"requested {n} devices, have {len(devs)}")
        devs = devs[:n]
    return devs


def build_mesh(
    devices: Sequence[jax.Device],
    data: Optional[int] = None,
    model: Optional[int] = None,
    seq: Optional[int] = None,
) -> Mesh:
    """Build a (data, model) mesh — or (data, seq, model) when ``seq`` is
    given — over ``devices``.

    Defaults: all devices on the data axis (other axes size 1) — the pure
    data-parallel shape. Any axis size may be given; one missing axis is
    derived. The ``seq`` axis is the ring for sequence/context parallelism
    (harmony_tpu.ops.ring); adjacent ring members are adjacent in the device
    order, so on hardware the ppermute rides neighbour ICI links.
    """
    n = len(devices)
    if seq is None:
        sizes = {"data": data, "model": model}
        names = (DATA_AXIS, MODEL_AXIS)
    else:
        sizes = {"data": data, "seq": seq, "model": model}
        names = (DATA_AXIS, SEQ_AXIS, MODEL_AXIS)
    unknown = [k for k, v in sizes.items() if v is None]
    known = int(np.prod([v for v in sizes.values() if v is not None])) or 1
    if len(unknown) > 1:
        if set(unknown) == {"data", "model"} and n % known == 0:
            sizes["data"], sizes["model"] = n // known, 1
        else:
            raise ValueError(f"underdetermined mesh axes {unknown}")
    elif len(unknown) == 1:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {sizes}")
        sizes[unknown[0]] = n // known
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(f"mesh {sizes} != num devices {n}")
    order = ("data", "seq", "model") if seq is not None else ("data", "model")
    arr = np.asarray(devices, dtype=object).reshape(*[sizes[k] for k in order])
    return Mesh(arr, names)


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh's devices live in more than one host process —
    the predicate gating single-controller-only paths (local probes,
    per-job optimizer loops, host-side snapshot reads)."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def _device_matches(
    d: jax.Device,
    device_kind: Optional[str],
    process_index: Optional[int],
) -> bool:
    """Per-request spec predicate (see DevicePool.lease)."""
    if device_kind is not None:
        kind = str(getattr(d, "device_kind", d.platform))
        if device_kind.lower() not in kind.lower():
            return False
    if process_index is not None and d.process_index != process_index:
        return False
    return True


class DevicePool:
    """Thread-safe pool of devices carved into per-job slices.

    The scheduling analogue of ResourcePool + EvaluatorManager: jobs request
    ``n`` devices and get a contiguous slice; releasing returns them. The
    default JobServer scheduler can also grant *all* devices to every job
    (multi-tenant overlap, ref: SchedulerImpl.java:28-66) — overlap is
    tracked so the TaskUnit scheduler knows which jobs share chips.
    """

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None) -> None:
        self._devices: List[jax.Device] = list(devices or jax.devices())
        self._lock = threading.Lock()
        self._leases: Dict[str, List[jax.Device]] = {}
        self._exclusive: Dict[str, bool] = {}

    @property
    def devices(self) -> List[jax.Device]:
        return list(self._devices)

    def __len__(self) -> int:
        return len(self._devices)

    def lease_all(self, job_id: str) -> List[jax.Device]:
        """Grant every device (shared; may overlap other leases)."""
        with self._lock:
            devs = list(self._devices)
            self._leases[job_id] = devs
            self._exclusive[job_id] = False
            return devs

    def lease(
        self,
        job_id: str,
        n: int,
        device_kind: Optional[str] = None,
        process_index: Optional[int] = None,
    ) -> List[jax.Device]:
        """Grant ``n`` exclusive devices (no overlap with other *exclusive*
        leases; shared lease_all leases coexist with anything).

        ``device_kind`` / ``process_index`` are PER-REQUEST resource specs —
        the heterogeneous-allocation analogue of the reference matching
        evaluator allocations to requests by node name and size (ref:
        services/evalmanager/impl/HeterogeneousEvalManager.java:40-70).
        ``device_kind`` is a case-insensitive substring of the platform's
        device kind (e.g. "v5 lite", "cpu"); ``process_index`` pins to one
        host of a multi-host pod. All-or-nothing like the homogeneous path.
        """
        with self._lock:
            taken = {
                d
                for j, ds in self._leases.items()
                if self._exclusive.get(j)
                for d in ds
            }
            free = [
                d for d in self._devices
                if d not in taken and _device_matches(d, device_kind, process_index)
            ]
            if len(free) < n:
                spec = ""
                if device_kind is not None or process_index is not None:
                    spec = (f" matching kind={device_kind!r}, "
                            f"process={process_index!r}")
                raise RuntimeError(
                    f"need {n} devices{spec}, only {len(free)} free"
                )
            devs = free[:n]
            self._leases[job_id] = devs
            self._exclusive[job_id] = True
            return devs

    def release(self, job_id: str) -> None:
        with self._lock:
            self._leases.pop(job_id, None)
            self._exclusive.pop(job_id, None)

    def lease_of(self, job_id: str) -> List[jax.Device]:
        with self._lock:
            return list(self._leases.get(job_id, []))

    def overlapping_jobs(self, job_id: str) -> List[str]:
        """Jobs whose leases share at least one device with ``job_id``'s."""
        with self._lock:
            mine = set(self._leases.get(job_id, []))
            return [
                j
                for j, ds in self._leases.items()
                if j != job_id and mine.intersection(ds)
            ]
