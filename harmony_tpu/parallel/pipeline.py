"""Pipeline parallelism — GPipe-style microbatched stages over a mesh axis.

Completes the framework's parallelism vocabulary (data / model-tensor /
sequence / **pipeline** / expert). No reference counterpart (the reference
scales only by table sharding, SURVEY.md §2.10); this is TPU-first design:

  * each device along the ``stage`` axis holds ONE stage's parameters
    (stage-stacked pytrees sharded on their leading axis),
  * microbatches stream through the ring: every tick each stage computes on
    its current microbatch and ``ppermute``s the activation to the next
    stage — the classic M + S - 1 tick schedule with bubbles masked out,
  * everything lives in one ``lax.scan`` inside one ``shard_map``, so XLA
    overlaps the ICI activation transfer of tick t with the compute of
    tick t+1, and autodiff through the scan gives the pipelined backward
    (activations rematerialized per-tick via jax.checkpoint).

``pipeline_apply`` is the inside-shard_map primitive; ``make_pipeline_fn``
wraps it for host-level use over a mesh.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

STAGE_AXIS = "stage"


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
    axis_name: str = STAGE_AXIS,
) -> jnp.ndarray:
    """Run ``microbatches [M, ...]`` through S pipelined stages.

    Call INSIDE shard_map: ``stage_params`` is the local stage's params
    (pytree), ``microbatches`` the full replicated input stream. Stage s
    applies ``stage_fn(stage_params, x)``; the composition over all stages
    is the pipelined function. Returns the [M, ...] outputs, replicated.
    """
    S = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    mb_shape = microbatches.shape[1:]
    # Activation carried between stages + output collection buffer. The
    # input stream is replicated (unvarying) but the carry becomes stage-
    # varying inside the scan — mark it so up front (shard_map vma typing).
    _vary = lambda a: lax.pcast(a, axis_name, to="varying")
    carry0 = _vary(jnp.zeros_like(microbatches[0]))
    outbuf0 = _vary(jnp.zeros((M, *mb_shape), microbatches.dtype))

    def tick(state, t):
        carry_in, outbuf = state
        # Stage 0 feeds microbatch t from the stream; later stages consume
        # the activation ppermuted from their predecessor.
        feed = microbatches[jnp.clip(t, 0, M - 1)]
        x = jnp.where(idx == 0, feed, carry_in)
        out = stage_fn(stage_params, x)
        # Stage idx processes microbatch m = t - idx; valid only in [0, M).
        m = t - idx
        active = (m >= 0) & (m < M)
        # Last stage banks its (active) outputs.
        mc = jnp.clip(m, 0, M - 1)
        write = active & (idx == S - 1)
        outbuf = outbuf.at[mc].set(
            jnp.where(write, out, outbuf[mc])
        )
        # Pass activations forward (the wrap-around S-1 -> 0 edge carries
        # garbage that stage 0 always overwrites with its feed).
        carry_out = lax.ppermute(out, axis_name, perm)
        return (carry_out, outbuf), None

    (_, outbuf), _ = lax.scan(
        jax.checkpoint(tick), (carry0, outbuf0), jnp.arange(T)
    )
    # Broadcast the last stage's collected outputs to every stage.
    return lax.psum(jnp.where(idx == S - 1, outbuf, jnp.zeros_like(outbuf)),
                    axis_name)


def make_pipeline_fn(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh,
    axis_name: str = STAGE_AXIS,
    num_microbatches: Optional[int] = None,
):
    """Host-level wrapper: ``fn(stacked_params, x) -> y`` where
    ``stacked_params`` pytree leaves have leading dim S (stage-stacked,
    sharded over ``axis_name``) and ``x [B, ...]`` is split into
    ``num_microbatches`` (default S) equal microbatches."""
    S = mesh.shape[axis_name]

    def fn(stacked_params, x):
        M = num_microbatches or S
        B = x.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        mb = x.reshape(M, B // M, *x.shape[1:])

        def local(params_stacked, mb_local):
            # shard_map gives each stage a leading dim of 1: unstack.
            params = jax.tree.map(lambda a: a[0], params_stacked)
            return pipeline_apply(stage_fn, params, mb_local, axis_name)

        out = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
        )(stacked_params, mb)
        return out.reshape(B, *out.shape[2:])

    return fn
