"""Multi-host wiring — the DCN side of the communication backend.

SURVEY.md §5.8 prescribes the split this framework implements: the DATA
plane is XLA collectives over ICI inside jitted steps (no counterpart of
the reference's per-key Netty RPCs needed), and the reference's
NameServer-based process bootstrap maps to JAX's distributed runtime:
``jax.distributed.initialize`` connects every host process to a
coordinator over DCN, after which ``jax.devices()`` is the GLOBAL device
list and a mesh built over it spans the pod — the same program text runs
single-host (this repo's tests, one chip or 8 virtual CPUs) and
multi-host (a pod slice) unchanged.

Single-host safe: every function degrades to a no-op/local equivalent, so
the framework never needs an "am I distributed?" fork in app code.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax
import numpy as np

from harmony_tpu.parallel.mesh import build_mesh

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host job (ref analogue: REEF NameServer registration,
    JobServerClient binding NameServerConfiguration — SURVEY.md §2.10).

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID). Returns True if a multi-process
    runtime was (or already is) initialized, False for the single-process
    no-op path.
    """
    global _initialized
    if _initialized:
        return True
    # IMPORTANT: decide from config BEFORE touching any jax API that could
    # initialize the XLA backend (jax.process_count() does) —
    # jax.distributed.initialize refuses to run after backend init.
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", 0))
    if not coordinator_address and num_processes <= 1:
        # No multi-host config of our own; report whether an external
        # launcher already initialized a multi-process runtime (safe to
        # query the backend here — we will not initialize).
        multi = jax.process_count() > 1
        _initialized = multi
        return multi
    # Half-configured launches must fail loudly: proceeding single-host
    # while peers block in jax.distributed.initialize is a silent hang plus
    # wrong-topology training. That includes a missing process id — every
    # host defaulting to id 0 conflicts at the coordinator.
    if not coordinator_address or num_processes <= 1:
        raise ValueError(
            "incomplete multi-host config: need BOTH a coordinator address "
            f"and num_processes > 1 (got coordinator={coordinator_address!r}, "
            f"num_processes={num_processes})"
        )
    pid_env = os.environ.get("JAX_PROCESS_ID")
    if process_id is None and pid_env is None:
        raise ValueError(
            "incomplete multi-host config: JAX_PROCESS_ID (or process_id=) "
            "is required when a coordinator is configured"
        )
    process_id = process_id if process_id is not None else int(pid_env)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        # jax 0.9 raises 'distributed.initialize should only be called once.'
        # or 'must be called before any JAX computations...' — message text
        # is unstable across versions, so decide from the OUTCOME: if a
        # multi-process runtime is in fact up, an external launcher beat us
        # to it and the documented contract is satisfied; otherwise the
        # failure is real (e.g. backend initialized too early single-host).
        if jax.process_count() > 1:
            _initialized = True
            return True
        raise
    _initialized = True
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multihost() -> bool:
    return jax.process_count() > 1


def global_devices() -> List[jax.Device]:
    """All devices across all hosts (== jax.devices(); addressable subset
    is jax.local_devices())."""
    return list(jax.devices())


def global_mesh(data=None, model=None, seq=None):
    """Mesh over the GLOBAL device list. On a pod slice JAX orders devices
    so that adjacent ids share ICI links; the (data, [seq,] model) reshape
    keeps each model/seq group intra-host where possible."""
    return build_mesh(global_devices(), data=data, model=model, seq=seq)


_MESH_SUM_CACHE: dict = {}


def mesh_sum(mesh, value: float, tag: str = "") -> float:
    """Sum a per-PROCESS scalar over ONLY the processes holding devices of
    ``mesh`` (each process contributes its value once, via its first
    addressable mesh device; the rest contribute zero). Doubles as the
    mesh-scoped barrier: the psum completes only when every participating
    process has dispatched it — unlike sync_global_devices this is safe
    for a CARVED mesh (the global barrier would wait on processes that
    never call it). ``tag`` is documentation/trace only: collectives match
    by the deterministic call sequence, not by name.

    Single-process meshes return ``value`` immediately."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from harmony_tpu.parallel.mesh import mesh_spans_processes

    if not mesh_spans_processes(mesh):
        return value
    axes = tuple(mesh.axis_names)
    fn = _MESH_SUM_CACHE.get(mesh)
    if fn is None:
        fn = jax.jit(jax.shard_map(
            lambda v: jax.lax.psum(v, axes), mesh=mesh,
            in_specs=P(axes), out_specs=P(),
        ))
        _MESH_SUM_CACHE[mesh] = fn
        while len(_MESH_SUM_CACHE) > 64:  # long-lived servers, many meshes
            _MESH_SUM_CACHE.pop(next(iter(_MESH_SUM_CACHE)))
    sharding = NamedSharding(mesh, P(axes))
    imap = sharding.addressable_devices_indices_map((mesh.devices.size,))
    shards = []
    first = True
    for d, idx in sorted(imap.items(), key=lambda kv: kv[1][0].start or 0):
        v = float(value) if first else 0.0
        first = False
        shards.append(jax.device_put(np.asarray([v], np.float32), d))
    arr = jax.make_array_from_single_device_arrays(
        (mesh.devices.size,), sharding, shards
    )
    return float(np.asarray(fn(arr)))  # replicated out: addressable D2H


def mesh_barrier(mesh, tag: str = "barrier") -> None:
    """Mesh-scoped barrier (see mesh_sum)."""
    mesh_sum(mesh, 0.0, tag)


def sync_global_devices(tag: str = "barrier") -> None:
    """Cross-host barrier: a tiny psum over every device; returns when all
    processes reached it (the analogue of the reference's driver-mediated
    sync acks). Single-host it is a trivially fast all-device reduction."""
    from jax.experimental import multihost_utils

    if is_multihost():
        multihost_utils.sync_global_devices(tag)
    else:
        # Single process: dispatch + block on a trivial all-device op so the
        # call still orders against in-flight work on every local device.
        x = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            np.ones((len(jax.local_devices()),), np.float32)
        )
        from harmony_tpu.utils.platform import hard_sync

        hard_sync(x)
