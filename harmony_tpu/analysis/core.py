"""harmonylint framework: file walker, finding type, pragmas, baseline.

Pure stdlib (``ast`` + ``re`` + ``json``) — the ``harmony-tpu lint``
subcommand rides the thin non-jax CLI path, so nothing in this module
(or any pass) may import jax or any harmony_tpu runtime module at
import time.

Vocabulary:

* A :class:`Pass` inspects a :class:`CodebaseIndex` (parsed sources +
  the doc/deploy artifacts consistency passes compare against) and
  yields :class:`Finding`\\ s anchored at ``file:line`` with a fix hint.
* An inline pragma ``# lint: allow(<pass>) <reason>`` on the finding
  line — or on a comment line directly above it — suppresses that
  pass's findings there. The reason is MANDATORY: a bare allow is
  itself reported (``pragma-hygiene``), because an unjustified
  suppression is exactly the drift this suite exists to stop.
* A baseline file (:func:`load_baseline` / :func:`save_baseline`)
  suppresses a known set of findings by line-independent key, for
  adopting a pass over a tree that has not been cleaned yet. The
  in-repo tree carries NO baseline — tier-1 runs the suite green.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import time
import tokenize
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# repo layout anchors, derived from this file's location
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)

PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z0-9_, -]+?)\s*\)\s*(.*)$")


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored and actionable."""

    pass_name: str
    file: str          #: repo-relative path, '/'-separated
    line: int
    message: str
    hint: str = ""     #: how to fix it (or where the convention lives)
    col: int = 0
    #: set by the framework when a pragma/baseline suppressed it
    suppressed_by: Optional[str] = None  # "pragma" | "baseline"
    pragma_reason: str = ""

    def key(self) -> str:
        """Line-independent identity used by baselines (lines drift on
        unrelated edits; pass+file+message does not)."""
        return f"{self.pass_name}::{self.file}::{self.message}"

    def format(self) -> str:
        s = f"{self.file}:{self.line}: [{self.pass_name}] {self.message}"
        if self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def to_json(self) -> Dict[str, Any]:
        return {
            "pass": self.pass_name,
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "suppressed_by": self.suppressed_by,
            "pragma_reason": self.pragma_reason or None,
        }


class SourceFile:
    """One parsed python file: source text, AST (None on syntax error —
    reported as a framework finding), and the pragma map."""

    def __init__(self, path: str, rel: str) -> None:
        self.path = path
        self.rel = rel
        # errors="replace": one stray non-UTF-8 byte must degrade into a
        # per-file parse finding, not kill the whole run
        with open(path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        self.parse_error_line: int = 1
        try:
            self.tree = ast.parse(self.text, filename=path)
        except SyntaxError as e:
            self.parse_error = str(e.msg)
            self.parse_error_line = int(e.lineno or 1)
        except ValueError as e:  # e.g. null bytes from the replace above
            self.parse_error = str(e)
        #: line -> [(frozenset(pass names) | {"*"}, reason)]
        self.pragmas: Dict[int, List[Tuple[frozenset, str]]] = {}
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        # tokenize, not regex-over-lines: '# lint: allow' inside a string
        # literal must not become a pragma
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if not m:
                    continue
                passes = frozenset(
                    p.strip() for p in m.group(1).split(",") if p.strip())
                self.pragmas.setdefault(tok.start[0], []).append(
                    (passes, m.group(2).strip()))
        except (tokenize.TokenError, SyntaxError):
            # tokenize raises IndentationError (a SyntaxError) on bad
            # dedents too; the parse-error finding covers this file
            pass

    def pragma_for(self, line: int, pass_name: str) -> Optional[Tuple[str, bool]]:
        """Returns (reason, valid) when an allow(<pass>) pragma covers
        ``line``: same line, or a run of comment-only lines directly
        above it. ``valid`` is False when the reason is empty."""
        candidates = list(self.pragmas.get(line, ()))
        lno = line - 1
        while lno >= 1 and lno <= len(self.lines):
            stripped = self.lines[lno - 1].strip()
            if not stripped.startswith("#"):
                break
            candidates.extend(self.pragmas.get(lno, ()))
            lno -= 1
        for passes, reason in candidates:
            if pass_name in passes or "*" in passes:
                return reason, bool(reason)
        return None


def _dotted_name(node: ast.AST) -> str:
    """'os.environ.get' for the func of a Call (best effort, '' when the
    expression is not a plain name/attribute chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def is_jit_call(node: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` (Name or Attribute form) — the ONE
    definition of "a jit wrapper" shared by jit-hygiene and
    use-after-donate, so the two passes can never disagree about which
    wrappers exist."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    return False


def _find_repo_root(start: str) -> str:
    """Walk up from ``start`` (inclusive — ``lint <repo root>`` must
    resolve to the repo root itself, not its parent) to the nearest dir
    holding pyproject.toml or docs/ — linting ``harmony_tpu/jobserver``
    must still find the real repo's doc/deploy artifacts, not look for
    docs under ``harmony_tpu/``. Falls back to dirname(start)."""
    d = start
    while True:
        if (os.path.isfile(os.path.join(d, "pyproject.toml"))
                or os.path.isdir(os.path.join(d, "docs"))):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.dirname(start)
        d = parent


class CodebaseIndex:
    """Parsed view of the tree a lint run inspects.

    ``root``: the package directory whose ``**/*.py`` are scanned.
    ``repo_root``: where ``docs/`` and ``deploy/gke/`` live — the
    consistency passes (fault-site-registry, knob-consistency) compare
    code against these artifacts. Fixture trees in tests point both at
    a miniature layout with the same shape.
    """

    def __init__(
        self,
        root: Optional[str] = None,
        repo_root: Optional[str] = None,
        files: Optional[Sequence[str]] = None,
        exclude: Optional[Sequence[str]] = None,
    ) -> None:
        self.root = os.path.abspath(root or _PKG_DIR)
        self.repo_root = os.path.abspath(
            repo_root or _find_repo_root(self.root))
        self.exclude = [e.strip("/") for e in (exclude or ())]
        self.files: List[SourceFile] = []
        #: partial runs see only a slice of the tree — explicit files, a
        #: subpackage dir below the repo's top level, or a non-package
        #: dir (`lint tests/`): "X exists nowhere in code" directions of
        #: the consistency passes are unanswerable there and skip
        #: walking the repo root itself is a SUPERSET of the default
        #: scan — a wider walk must never report fewer findings than
        #: the narrow one, so it keeps the repo-wide directions
        self.partial = files is not None or (
            self.root != self.repo_root
            and (os.path.dirname(self.root) != self.repo_root
                 or not os.path.isfile(
                     os.path.join(self.root, "__init__.py"))))
        if files is not None:
            # explicitly named files are linted even under an exclude
            # prefix — the fixture tests (and a curious operator) point
            # straight at known-bad files on purpose
            paths = [os.path.abspath(p) for p in files]
        else:
            paths = []
            for dirpath, dirnames, names in os.walk(self.root):
                dirnames[:] = [
                    d for d in dirnames
                    if d != "__pycache__"
                    and not self._excluded(os.path.join(dirpath, d))]
                for n in sorted(names):
                    if (n.endswith(".py")
                            and not self._excluded(
                                os.path.join(dirpath, n))):
                        paths.append(os.path.join(dirpath, n))
        for p in sorted(paths):
            self.files.append(SourceFile(p, self._rel(p)))

    def _excluded(self, path: str) -> bool:
        """True when ``path`` sits under a configured exclude prefix
        (repo-root-relative)."""
        if not self.exclude:
            return False
        rel = os.path.relpath(path, self.repo_root).replace(os.sep, "/")
        if rel.startswith(".."):
            return False
        return any(rel == e or rel.startswith(e + "/")
                   for e in self.exclude)

    def _rel(self, path: str) -> str:
        base = (self.repo_root
                if path.startswith(self.repo_root) else self.root)
        return os.path.relpath(path, base).replace(os.sep, "/")

    # -- artifacts the consistency passes compare against ----------------

    def doc_path(self, name: str) -> str:
        return os.path.join(self.repo_root, "docs", name)

    def doc_text(self, name: str) -> str:
        """docs/<name> contents ('' when absent — passes report absence
        themselves when the artifact is load-bearing)."""
        try:
            with open(self.doc_path(name), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""

    def doc_texts(self) -> Dict[str, str]:
        """Every docs/*.md, keyed by repo-relative path."""
        out: Dict[str, str] = {}
        docs = os.path.join(self.repo_root, "docs")
        if os.path.isdir(docs):
            for n in sorted(os.listdir(docs)):
                if n.endswith(".md"):
                    out[f"docs/{n}"] = self.doc_text(n)
        return out

    def deploy_manifests(self) -> Dict[str, str]:
        """deploy/gke/*.yaml raw text, keyed by repo-relative path."""
        out: Dict[str, str] = {}
        d = os.path.join(self.repo_root, "deploy", "gke")
        if os.path.isdir(d):
            for n in sorted(os.listdir(d)):
                if n.endswith((".yaml", ".yml")):
                    with open(os.path.join(d, n), encoding="utf-8") as f:
                        out[f"deploy/gke/{n}"] = f.read()
        return out

    def repo_py_texts(self) -> Dict[str, str]:
        """Raw text of every tracked-ish .py under repo_root (scanned
        tree + tests/benchmarks/bench.py) — for 'is this knob read
        ANYWHERE' style questions that are wider than the lint root."""
        out = {sf.rel: sf.text for sf in self.files}
        for extra in ("tests", "benchmarks"):
            d = os.path.join(self.repo_root, extra)
            if not os.path.isdir(d):
                continue
            for dirpath, dirnames, names in os.walk(d):
                dirnames[:] = [x for x in dirnames if x != "__pycache__"]
                for n in names:
                    if n.endswith(".py"):
                        p = os.path.join(dirpath, n)
                        rel = os.path.relpath(
                            p, self.repo_root).replace(os.sep, "/")
                        try:
                            with open(p, encoding="utf-8") as f:
                                out[rel] = f.read()
                        except OSError:
                            continue
        bench = os.path.join(self.repo_root, "bench.py")
        if os.path.isfile(bench):
            with open(bench, encoding="utf-8") as f:
                out["bench.py"] = f.read()
        return out


class Pass:
    """Base class: subclasses set ``name``/``description`` and implement
    :meth:`run`. Findings they emit should use ``self.finding(...)`` so
    the pass name is stamped consistently."""

    name: str = ""
    description: str = ""

    def run(self, index: CodebaseIndex) -> List[Finding]:
        raise NotImplementedError

    def finding(self, file: str, line: int, message: str,
                hint: str = "", col: int = 0) -> Finding:
        return Finding(pass_name=self.name, file=file, line=line,
                       message=message, hint=hint, col=col)


@dataclasses.dataclass
class LintConfig:
    """Resolved run configuration (CLI flags over ``[tool.harmony.lint]``
    in pyproject.toml over defaults)."""

    enable: Optional[List[str]] = None    # None = all registered passes
    disable: List[str] = dataclasses.field(default_factory=list)
    baseline: Optional[str] = None
    #: repo-root-relative path prefixes the directory walk skips —
    #: this repo excludes tests/fixtures/lint (deliberately-bad lint
    #: fodder; linting it red is the fixtures doing their job, not a
    #: finding). Explicitly named files are always linted.
    exclude: List[str] = dataclasses.field(default_factory=list)

    def selected(self, all_names: Sequence[str]) -> List[str]:
        names = list(self.enable) if self.enable else list(all_names)
        unknown = [n for n in names + self.disable if n not in all_names]
        if unknown:
            raise ValueError(f"unknown lint pass(es): {unknown}; "
                             f"known: {sorted(all_names)}")
        return [n for n in names if n not in self.disable]


def _parse_toml_section(text: str, section: str) -> Dict[str, Any]:
    """Minimal TOML reader for one table: strings, string arrays, bools.
    Python 3.10 has no tomllib; pulling in a TOML dependency for three
    keys would violate the no-new-deps rule, so this reads exactly the
    subset ``[tool.harmony.lint]`` uses (tomllib is preferred when the
    interpreter has it)."""
    try:
        import tomllib  # py>=3.11

        data = tomllib.loads(text)
        for part in section.split("."):
            data = data.get(part, {})
        return data if isinstance(data, dict) else {}
    except ImportError:
        pass
    out: Dict[str, Any] = {}
    in_section = False
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip() if not raw.strip().startswith(
            "#") else ""
        if not line:
            continue
        if line.startswith("["):
            in_section = line == f"[{section}]"
            continue
        if not in_section or "=" not in line:
            continue
        key, val = (s.strip() for s in line.split("=", 1))
        if val.startswith("["):
            out[key] = re.findall(r'"([^"]*)"', val)
        elif val.startswith('"'):
            out[key] = val.strip('"')
        elif val in ("true", "false"):
            out[key] = val == "true"
        else:
            try:
                out[key] = int(val)
            except ValueError:
                out[key] = val
    return out


def load_config(repo_root: Optional[str] = None) -> LintConfig:
    """``[tool.harmony.lint]`` from <repo_root>/pyproject.toml (defaults
    when the file or section is absent)."""
    path = os.path.join(repo_root or REPO_ROOT, "pyproject.toml")
    cfg = LintConfig()
    try:
        with open(path, encoding="utf-8") as f:
            raw = _parse_toml_section(f.read(), "tool.harmony.lint")
    except OSError:
        return cfg
    if raw.get("enable"):
        cfg.enable = list(raw["enable"])
    if raw.get("disable"):
        cfg.disable = list(raw["disable"])
    if raw.get("baseline"):
        cfg.baseline = str(raw["baseline"])
    if raw.get("exclude"):
        cfg.exclude = list(raw["exclude"])
    return cfg


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    """Finding keys a previous run accepted (schema: {"version": 1,
    "entries": [key, ...]})."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"{path}: not a harmonylint baseline (version 1)")
    entries = data.get("entries", [])
    if not all(isinstance(e, str) for e in entries):
        raise ValueError(f"{path}: baseline entries must be strings")
    return list(entries)


def save_baseline(result: "LintResult", path: str) -> int:
    """Write the ACTIVE findings of ``result`` as the new baseline;
    returns the entry count. Suppressed findings are not re-baselined."""
    entries = sorted({f.key() for f in result.findings})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return len(entries)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          #: active (fail the run)
    suppressed: List[Finding]        #: pragma- or baseline-suppressed
    passes_run: List[str]
    files_scanned: int
    wall_ms: float
    root: str

    @property
    def ok(self) -> bool:
        return not self.findings


class PragmaHygienePass(Pass):
    """Findings the framework itself owns: unparseable files and
    reason-less pragmas (both would otherwise silently shrink
    coverage). Registered like any pass (so ``--passes`` /
    ``--list-passes`` / ``disable`` all know its name) but ALSO
    prepended to every run unless explicitly disabled — suppressions
    stay justified even under a ``--passes`` subset."""

    name = "pragma-hygiene"
    description = ("files must parse, and every `# lint: allow(...)` "
                   "pragma must carry a justification")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.parse_error is not None:
                # line rides the anchor, NOT the message — Finding.key()
                # is the line-independent baseline identity
                out.append(self.finding(
                    sf.rel, sf.parse_error_line,
                    f"file does not parse: {sf.parse_error}",
                    hint="a file the passes cannot read is a hole in "
                         "every invariant this suite pins"))
            for line, entries in sorted(sf.pragmas.items()):
                for passes, reason in entries:
                    if not reason:
                        out.append(self.finding(
                            sf.rel, line,
                            "allow({}) pragma without a reason".format(
                                ",".join(sorted(passes))),
                            hint="say WHY the rule does not apply here — "
                                 "`# lint: allow(<pass>) <justification>`"))
        return out


def run_lint(
    root: Optional[str] = None,
    passes: Optional[Sequence[Pass]] = None,
    config: Optional[LintConfig] = None,
    baseline: Optional[Sequence[str]] = None,
    repo_root: Optional[str] = None,
    files: Optional[Sequence[str]] = None,
) -> LintResult:
    """Run the suite; returns a :class:`LintResult` whose ``findings``
    are the unsuppressed problems (empty = green)."""
    from harmony_tpu.analysis.passes import all_passes

    t0 = time.perf_counter()
    root_abs = os.path.abspath(root or _PKG_DIR)
    repo_abs = os.path.abspath(repo_root or _find_repo_root(root_abs))
    cfg = config or load_config(repo_abs)
    index = CodebaseIndex(root=root_abs, repo_root=repo_abs, files=files,
                          exclude=cfg.exclude)
    if passes is None:
        registry = {p.name: p for p in all_passes()}
        selected = cfg.selected(list(registry))
        run_list = [registry[n] for n in selected]
    else:
        run_list = list(passes)
    if (not any(p.name == PragmaHygienePass.name for p in run_list)
            and PragmaHygienePass.name not in cfg.disable):
        run_list = [PragmaHygienePass()] + run_list
    if baseline is None and cfg.baseline:
        baseline = load_baseline(
            os.path.join(index.repo_root, cfg.baseline))
    baseline_keys = set(baseline or ())

    by_rel = {sf.rel: sf for sf in index.files}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for p in run_list:
        for f in p.run(index):
            sf = by_rel.get(f.file)
            pragma = (sf.pragma_for(f.line, p.name)
                      if sf is not None else None)
            if pragma is not None and pragma[1]:
                f.suppressed_by = "pragma"
                f.pragma_reason = pragma[0]
                suppressed.append(f)
            elif f.key() in baseline_keys:
                f.suppressed_by = "baseline"
                suppressed.append(f)
            else:
                active.append(f)
    order = {p.name: i for i, p in enumerate(run_list)}
    active.sort(key=lambda f: (f.file, f.line, order.get(f.pass_name, 99)))
    suppressed.sort(key=lambda f: (f.file, f.line))
    return LintResult(
        findings=active,
        suppressed=suppressed,
        passes_run=[p.name for p in run_list],
        files_scanned=len(index.files),
        wall_ms=round((time.perf_counter() - t0) * 1000.0, 2),
        root=index.root,
    )


# -- output -----------------------------------------------------------------

def render_text(result: LintResult, verbose: bool = False) -> str:
    out: List[str] = []
    for f in result.findings:
        out.append(f.format())
    if verbose:
        for f in result.suppressed:
            out.append(f"{f.file}:{f.line}: [{f.pass_name}] suppressed "
                       f"({f.suppressed_by}"
                       + (f": {f.pragma_reason}" if f.pragma_reason else "")
                       + f") {f.message}")
    out.append(
        f"harmonylint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, "
        f"{result.files_scanned} files, "
        f"{len(result.passes_run)} passes, {result.wall_ms:.0f} ms")
    return "\n".join(out)


def render_json(result: LintResult) -> str:
    """Stable machine-readable schema (pinned by tests/test_analysis.py
    — CI consumers parse this, bump "version" on shape changes)."""
    return json.dumps({
        "version": 1,
        "root": result.root,
        "passes": result.passes_run,
        "files_scanned": result.files_scanned,
        "wall_ms": result.wall_ms,
        "ok": result.ok,
        "findings": [f.to_json() for f in result.findings],
        "suppressed": [f.to_json() for f in result.suppressed],
    }, indent=1, sort_keys=True)
