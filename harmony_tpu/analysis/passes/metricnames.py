"""metric-conventions: instrument declarations obey the exposition
contract at the declaration site, and instrument ⇄ doc-table parity.

The scrape-time grammar/semantic linter (``metrics.registry
.lint_exposition``, tier-1 since PR 4) catches a bad family name only
when a scrape happens to render it; this pass pins the same naming
conventions STATICALLY on every ``registry.counter/gauge/histogram/
register_callback`` call with a literal name, so a typo'd family fails
lint before it ever reaches an exporter:

* names are ``harmony_``-prefixed snake_case (the label-join and
  dashboards key on the prefix),
* counters end ``_total`` (the rule lint_exposition enforces at scrape
  time — Prometheus rate() semantics),
* histograms end in a base unit (``_seconds`` / ``_bytes``) per the
  OpenMetrics unit convention docs/OBSERVABILITY.md documents,
* the HELP string is non-empty (a help-less family renders a lint
  failure at scrape time).

Plus the doc-parity directions (mirroring knob-consistency's shape):

* every instrument REGISTERED in the tree appears in the
  docs/OBSERVABILITY.md metric table — an undocumented instrument is a
  number operators cannot interpret (the table is the metric glossary);
* every ``harmony_*`` name a metric-table row documents is registered
  somewhere — a documented-but-unregistered metric is a dashboard query
  that silently returns nothing.

Plus the DOCTOR-RULE parity directions (same shape, different
registry): every rule declared through ``doctor_rule("name", ...)``
(metrics/doctor.py) appears as a row in the OBSERVABILITY.md "Rule
catalog" table, and every catalog row names a shipped rule — an
undocumented rule is a verdict operators cannot interpret, and a
documented-but-unshipped rule is a diagnosis that will never fire.

All parity directions need the WHOLE tree and the real docs to mean
anything, so they are skipped on partial runs (explicit files / dir
slices — the fixture corpus lints file-by-file and must not be
compared against the real repo's table).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass, _str_const

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HISTO_UNITS = ("_seconds", "_bytes")
_METHODS = ("counter", "gauge", "histogram", "register_callback")
#: full instrument names in doc TABLE rows (lowercase by convention —
#: the knob tables' HARMONY_* env names never collide with this)
_DOC_METRIC_RE = re.compile(r"harmony_[a-z][a-z0-9_]*")
_METRIC_DOC = "OBSERVABILITY.md"


def _registered_instruments(
    tree: ast.AST, rel: str
) -> List[Tuple[str, str, int]]:
    """(name, method, line) for every registry-method call with a
    literal ``harmony_*`` first argument in one module."""
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS
                and node.args):
            continue
        mname = _str_const(node.args[0])
        if mname is None or not mname.startswith("harmony_"):
            continue
        out.append((mname, node.func.attr, node.lineno))
    return out


#: the doctor-rule declaration callable (metrics/doctor.py) — literal
#: first args are the shipped rule names
_RULE_CALL = "doctor_rule"
#: a rule name inside a catalog-table row: the FIRST backticked
#: snake_case token of the row
_DOC_RULE_RE = re.compile(r"`([a-z][a-z0-9_]*)`")
_RULE_HEADING = "rule catalog"


def _declared_rules(tree: ast.AST) -> List[Tuple[str, int]]:
    """(rule_name, line) for every ``doctor_rule("name", ...)`` call
    with a literal first argument in one module."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fn = node.func
        fname = (fn.attr if isinstance(fn, ast.Attribute)
                 else fn.id if isinstance(fn, ast.Name) else None)
        if fname != _RULE_CALL:
            continue
        rname = _str_const(node.args[0])
        if rname is not None:
            out.append((rname, node.lineno))
    return out


def _doc_rule_catalog(index: CodebaseIndex) -> Dict[str, int]:
    """Rule names in the OBSERVABILITY.md *Rule catalog* table -> line
    number: table rows (``|``-prefixed) between a heading containing
    "Rule catalog" and the next heading; the row's FIRST backticked
    token is the rule name. Prose name-drops elsewhere do not count —
    the catalog row (predicate, evidence format) is the operator
    contract this pass pins."""
    out: Dict[str, int] = {}
    in_section = False
    for lno, line in enumerate(
            index.doc_text(_METRIC_DOC).splitlines(), start=1):
        if line.lstrip().startswith("#"):
            in_section = _RULE_HEADING in line.lower()
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        stripped = line.strip().strip("|").strip()
        if set(stripped) <= {"-", "|", " ", ":"}:
            continue  # the separator row
        m = _DOC_RULE_RE.search(line)
        if m:
            out.setdefault(m.group(1), lno)
    return out


def _doc_table_metrics(index: CodebaseIndex) -> Dict[str, int]:
    """Instrument names in docs/OBSERVABILITY.md TABLE rows (lines
    starting with ``|``) -> first line number. Prose name-drops give an
    operator no source/meaning row and do not count — the same
    table-row rule knob-consistency applies to the knob docs."""
    out: Dict[str, int] = {}
    for lno, line in enumerate(
            index.doc_text(_METRIC_DOC).splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        for name in _DOC_METRIC_RE.findall(line):
            out.setdefault(name, lno)
    return out


class MetricConventionsPass(Pass):
    name = "metric-conventions"
    description = ("registry instrument names satisfy the exposition "
                   "lint's conventions and match the OBSERVABILITY.md "
                   "metric table (both directions)")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        registered: List[Tuple[str, str, str, int]] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            for mname, _method, lineno in _registered_instruments(
                    sf.tree, sf.rel):
                registered.append((mname, _method, sf.rel, lineno))
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _METHODS
                        and node.args):
                    continue
                mname = _str_const(node.args[0])
                if mname is None or not mname.startswith("harmony_"):
                    # non-literal or foreign-prefix names are out of
                    # scope (the prefix is what routes to OUR registry
                    # conventions; .counter() on arbitrary objects must
                    # not trip this pass)
                    continue
                kind = node.func.attr
                if kind == "register_callback":
                    kind_arg = (node.args[2] if len(node.args) > 2 else
                                next((k.value for k in node.keywords
                                      if k.arg == "kind"), None))
                    kind = _str_const(kind_arg) if kind_arg is not None \
                        else None
                if not _NAME_RE.match(mname):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"metric name {mname!r} is not snake_case",
                        hint="exposition renders family names verbatim; "
                             "see docs/OBSERVABILITY.md naming table"))
                if kind == "counter" and not mname.endswith("_total"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"harmony_* counter {mname!r} must end _total",
                        hint="same rule lint_exposition enforces at "
                             "scrape time — fix the name here, not the "
                             "scrape"))
                if (kind == "histogram"
                        and not mname.endswith(_HISTO_UNITS)):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"histogram {mname!r} lacks a base-unit suffix "
                        f"({'/'.join(_HISTO_UNITS)})",
                        hint="observe() values are seconds or bytes "
                             "everywhere in this tree; name the unit"))
                help_arg = (node.args[1] if len(node.args) > 1 else
                            next((k.value for k in node.keywords
                                  if k.arg == "help"), None))
                help_lit = _str_const(help_arg) if help_arg is not None \
                    else None
                # absent help is as bad as empty help (and contrary to
                # first appearances, scrape-time lint_exposition does
                # NOT catch either: the exporter renders `# HELP name `
                # which parses back as help="", not None)
                if help_arg is None or help_lit == "":
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"instrument {mname!r} declared with an empty "
                        "or missing HELP string",
                        hint="one sentence: what the number means and "
                             "its unit"))

        if index.partial:
            # a file slice can neither prove a doc row is registered
            # nowhere nor is its (often fixture) content part of the
            # operator surface the table documents
            return out

        # -- doctor-rule <-> rule-catalog parity (both directions) -----
        declared_rules: List[Tuple[str, str, int]] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            for rname, lineno in _declared_rules(sf.tree):
                declared_rules.append((rname, sf.rel, lineno))
        doc_rules = _doc_rule_catalog(index)
        doc_rel = f"docs/{_METRIC_DOC}"
        if declared_rules and not doc_rules:
            out.append(self.finding(
                doc_rel, 1,
                "doctor rules are declared but docs/OBSERVABILITY.md "
                "has no 'Rule catalog' table",
                hint="add the catalog (rule | predicate | evidence "
                     "rows) — the table is the verdict glossary this "
                     "pass checks against"))
        else:
            declared_names = {r for r, _f, _l in declared_rules}
            for rname, rel, lineno in declared_rules:
                if rname not in doc_rules:
                    out.append(self.finding(
                        rel, lineno,
                        f"doctor rule {rname!r} is declared here but "
                        "appears in no OBSERVABILITY.md rule-catalog "
                        "row",
                        hint="add a `rule | predicate | evidence` row "
                             "— an undocumented rule is a verdict "
                             "operators cannot interpret"))
            for rname, lno in sorted(doc_rules.items()):
                if rname not in declared_names:
                    out.append(self.finding(
                        doc_rel, lno,
                        f"rule catalog documents {rname!r} but no "
                        "doctor_rule() declares it",
                        hint="a documented-but-unshipped rule is a "
                             "diagnosis that will never fire; fix the "
                             "row or ship the rule"))

        documented = _doc_table_metrics(index)
        if not documented:
            if registered:
                # no metric table resolvable (docs/ absent — e.g. a
                # site-packages install): one structural finding, not
                # one per instrument
                out.append(self.finding(
                    doc_rel, 1,
                    "no metric table found in docs/OBSERVABILITY.md "
                    "(lines starting with '|' naming harmony_* families)",
                    hint="run the lint from the repo root — the table "
                         "is the metric glossary this pass checks "
                         "against"))
            return out
        for mname, _method, rel, lineno in registered:
            if mname not in documented:
                out.append(self.finding(
                    rel, lineno,
                    f"instrument {mname} is registered here but appears "
                    "in no docs/OBSERVABILITY.md metric-table row",
                    hint="add a `metric | source` row — an undocumented "
                         "instrument is a number operators cannot "
                         "interpret"))
        # the reverse direction needs the WIDER surface (tests and
        # benchmarks legitimately register probe instruments), same as
        # knob-consistency's read scan; an unparseable file degrades to
        # a raw-text scan rather than marking its instruments missing
        reg_names: Set[str] = {m for m, _k, _r, _l in registered}
        scanned = {sf.rel for sf in index.files}
        for rel, text in index.repo_py_texts().items():
            if rel in scanned:
                continue
            try:
                tree = ast.parse(text)
            except (SyntaxError, ValueError):
                reg_names.update(_DOC_METRIC_RE.findall(text))
                continue
            reg_names.update(
                m for m, _k, _l in _registered_instruments(tree, rel))
        for name, lno in sorted(documented.items()):
            if name not in reg_names:
                out.append(self.finding(
                    doc_rel, lno,
                    f"metric table documents {name} but nothing in the "
                    "repo registers it",
                    hint="a documented-but-unregistered metric is a "
                         "dashboard query that silently returns "
                         "nothing; fix the row or wire the instrument"))
        return out
