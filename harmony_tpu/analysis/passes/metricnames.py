"""metric-conventions: instrument declarations obey the exposition
contract at the declaration site.

The scrape-time grammar/semantic linter (``metrics.registry
.lint_exposition``, tier-1 since PR 4) catches a bad family name only
when a scrape happens to render it; this pass pins the same naming
conventions STATICALLY on every ``registry.counter/gauge/histogram/
register_callback`` call with a literal name, so a typo'd family fails
lint before it ever reaches an exporter:

* names are ``harmony_``-prefixed snake_case (the label-join and
  dashboards key on the prefix),
* counters end ``_total`` (the rule lint_exposition enforces at scrape
  time — Prometheus rate() semantics),
* histograms end in a base unit (``_seconds`` / ``_bytes``) per the
  OpenMetrics unit convention docs/OBSERVABILITY.md documents,
* the HELP string is non-empty (a help-less family renders a lint
  failure at scrape time).
"""
from __future__ import annotations

import ast
import re
from typing import List

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass, _str_const

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HISTO_UNITS = ("_seconds", "_bytes")
_METHODS = ("counter", "gauge", "histogram", "register_callback")


class MetricConventionsPass(Pass):
    name = "metric-conventions"
    description = ("registry instrument names satisfy the exposition "
                   "lint's conventions at the declaration site")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _METHODS
                        and node.args):
                    continue
                mname = _str_const(node.args[0])
                if mname is None or not mname.startswith("harmony_"):
                    # non-literal or foreign-prefix names are out of
                    # scope (the prefix is what routes to OUR registry
                    # conventions; .counter() on arbitrary objects must
                    # not trip this pass)
                    continue
                kind = node.func.attr
                if kind == "register_callback":
                    kind_arg = (node.args[2] if len(node.args) > 2 else
                                next((k.value for k in node.keywords
                                      if k.arg == "kind"), None))
                    kind = _str_const(kind_arg) if kind_arg is not None \
                        else None
                if not _NAME_RE.match(mname):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"metric name {mname!r} is not snake_case",
                        hint="exposition renders family names verbatim; "
                             "see docs/OBSERVABILITY.md naming table"))
                if kind == "counter" and not mname.endswith("_total"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"harmony_* counter {mname!r} must end _total",
                        hint="same rule lint_exposition enforces at "
                             "scrape time — fix the name here, not the "
                             "scrape"))
                if (kind == "histogram"
                        and not mname.endswith(_HISTO_UNITS)):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"histogram {mname!r} lacks a base-unit suffix "
                        f"({'/'.join(_HISTO_UNITS)})",
                        hint="observe() values are seconds or bytes "
                             "everywhere in this tree; name the unit"))
                help_arg = (node.args[1] if len(node.args) > 1 else
                            next((k.value for k in node.keywords
                                  if k.arg == "help"), None))
                help_lit = _str_const(help_arg) if help_arg is not None \
                    else None
                # absent help is as bad as empty help (and contrary to
                # first appearances, scrape-time lint_exposition does
                # NOT catch either: the exporter renders `# HELP name `
                # which parses back as help="", not None)
                if help_arg is None or help_lit == "":
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"instrument {mname!r} declared with an empty "
                        "or missing HELP string",
                        hint="one sentence: what the number means and "
                             "its unit"))
        return out
