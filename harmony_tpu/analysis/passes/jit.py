"""jit-hygiene: no construct-and-call jit; step-shaped jits declare
donation intent.

The bug class PR 6 fixed (apps/nmf.py, apps/lda.py,
checkpoint/orbax_io.py, pregel/master.py): building a FRESH ``jax.jit``
wrapper inside a lambda/loop that runs per invocation — each call makes
a new Python closure, so jax's executable cache can never hit and the
program retraces (and recompiles) every time. Two rules:

1. no construct-and-call — ``jax.jit(...)(...)`` / ``pjit(...)(...)``
   in one expression builds a wrapper and throws it away after one
   call. Hoist the wrapper (module scope, a table's ``_jitted`` cache,
   or runtime/progcache). The one vouched-for one-shot site
   (table/autotune.py) carries an inline allow pragma.
2. step-shaped jits declare donation intent — any ``jax.jit(fn)`` whose
   traced function is named like a training step (``*step*``,
   ``*epoch*``, ``*superstep*``) must pass ``donate_argnums``
   EXPLICITLY (``()`` is fine: it says "this step deliberately does not
   donate"). Donation is the fused hot path's memory contract; an
   implicit default on a step is how a double-buffered table silently
   doubles HBM.
"""
from __future__ import annotations

import ast
import re
from typing import List

from harmony_tpu.analysis.core import (
    CodebaseIndex,
    Finding,
    Pass,
    is_jit_call,
)

STEP_NAME = re.compile(r"(^|_)(step|epoch|superstep)", re.IGNORECASE)


def _is_jit_call(node: ast.Call) -> bool:
    return is_jit_call(node.func)


class JitHygienePass(Pass):
    name = "jit-hygiene"
    description = ("jit wrappers are cached (no construct-and-call) and "
                   "step-shaped jits declare donate_argnums explicitly")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if (isinstance(node.func, ast.Call)
                        and _is_jit_call(node.func)):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        "jit wrapper constructed and invoked in one "
                        "expression (retraces every call)",
                        hint="hoist it into a cached wrapper — "
                             "table._jitted / runtime.progcache / module "
                             "scope", col=node.col_offset))
                if _is_jit_call(node) and node.args:
                    target = node.args[0]
                    if (isinstance(target, ast.Name)
                            and STEP_NAME.search(target.id)
                            and "donate_argnums" not in {
                                k.arg for k in node.keywords}):
                        out.append(self.finding(
                            sf.rel, node.lineno,
                            f"step-shaped jit({target.id}) without an "
                            "explicit donate_argnums",
                            hint="pass donate_argnums=() to declare a "
                                 "deliberate non-donating step",
                            col=node.col_offset))
        return out
