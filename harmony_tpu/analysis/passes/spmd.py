"""spmd-divergence: per-process state never steers SPMD dispatch.

The PR 5 chunk-count rule, generalized. On a multi-process mesh every
process must trace and dispatch the IDENTICAL sequence of collective
programs ("Exploring the limits of Concurrency in ML Training on
Google TPUs" — concurrency correctness at pod scale hinges on it); a
value that can differ per process — an env knob, the wall clock, a
random draw — steering how many times (or whether) a collective
dispatches wedges the pod, usually hours into a run, always on the
process you are not looking at. PR 5 hit exactly this: a restore chunk
count derived from this process's ``HARMONY_CHKP_IO_THREADS`` gating
``import_blocks`` (an SPMD-collective dispatch on spanning meshes).

The pass flags a dispatch-marker call (collectives + ``import_blocks``
/ ``mesh_sum``-style repo primitives) whose governing control flow —
enclosing ``if`` tests, ``while`` tests, ``for`` iterables in the same
function — is tainted by per-process state:

* direct: ``os.environ``/``os.getenv``/``env_*`` reads, ``HARMONY_*``
  literals in calls, ``time.*`` clocks, ``random``-ish draws;
* transitive: locals assigned from tainted expressions, and calls to
  same-module functions that read such state.

The sanctioned idiom is structural, not a pragma: derive the
process-uniform decision WITH a topology guard —
``... and not mesh_spans_processes(mesh)`` (or ``process_count()``)
— in the same condition chain, the way checkpoint/manager.py's
pipelined restore does. A control chain that consults a topology guard
anywhere in its derivation is accepted.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass, _dotted_name

DISPATCH_MARKERS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pbroadcast", "process_allgather",
    "broadcast_one_to_all", "import_blocks", "mesh_sum",
}
UNIFORM_GUARDS = {
    "mesh_spans_processes", "spans_processes", "process_count",
    "process_index", "is_multiprocess", "single_process",
}
_TIME_FUNCS = {"time", "monotonic", "perf_counter", "time_ns", "clock"}
_RANDOM_FUNCS = {"random", "randint", "randrange", "shuffle", "choice",
                 "uniform", "gauss", "sample"}


def _is_divergent_call(node: ast.Call) -> Optional[str]:
    """Why this call reads per-process state (None when it doesn't)."""
    dotted = _dotted_name(node.func)
    parts = dotted.split(".") if dotted else []
    if parts:
        last = parts[-1]
        if "environ" in parts or last == "getenv":
            return "env read"
        if last.startswith("env_"):
            return "env read"
        if "time" in parts[:-1] and last in _TIME_FUNCS:
            return "clock read"
        if len(parts) == 1 and last in ("monotonic", "perf_counter",
                                        "time_ns"):
            return "clock read"
        if "random" in parts[:-1] and last in _RANDOM_FUNCS | {"rand",
                                                               "randn"}:
            return "random draw"
        if len(parts) == 1 and last in _RANDOM_FUNCS - {"random"}:
            return "random draw"
    for arg in ast.walk(node):
        if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                and arg.value.startswith("HARMONY_")):
            return f"env read ({arg.value})"
    return None


def _contains_divergence(expr: ast.AST,
                         tainted: Set[str],
                         divergent_funcs: Set[str]) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            why = _is_divergent_call(node)
            if why:
                return why
            dotted = _dotted_name(node.func)
            if dotted and dotted.rsplit(".", 1)[-1] in divergent_funcs:
                return f"call to {dotted}() which reads per-process state"
        elif isinstance(node, ast.Subscript):
            if _dotted_name(node.value).endswith("environ"):
                return "env read"
        elif isinstance(node, ast.Name) and node.id in tainted:
            return f"value derived from per-process state ({node.id})"
    return None


def _contains_guard(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        dotted = _dotted_name(node if not isinstance(node, ast.Call)
                              else node.func)
        if dotted and dotted.rsplit(".", 1)[-1] in UNIFORM_GUARDS:
            return True
    return False


def _own_statements(fn: ast.AST) -> List[ast.stmt]:
    """Function body statements excluding nested def/class bodies (those
    are separate analyses)."""
    return list(fn.body)


def _walk_own(stmts: Sequence[ast.stmt]):
    """Yield every node in these statements, not descending into nested
    function/class scopes."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class SpmdDivergencePass(Pass):
    name = "spmd-divergence"
    description = ("env/clock/random state never controls whether or "
                   "how many times an SPMD collective dispatches")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            funcs = [n for n in ast.walk(sf.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            divergent_funcs = self._divergent_funcs(funcs)
            for fn in funcs:
                out.extend(self._check_function(sf.rel, fn,
                                                divergent_funcs))
        return out

    def _divergent_funcs(self, funcs: Sequence[ast.AST]) -> Set[str]:
        """Same-module functions that (transitively) read per-process
        state — matched by bare name at call sites."""
        direct: Set[str] = set()
        calls: Dict[str, Set[str]] = {}
        for fn in funcs:
            called: Set[str] = set()
            for node in _walk_own(fn.body):
                if isinstance(node, ast.Call):
                    if _is_divergent_call(node):
                        direct.add(fn.name)
                    dotted = _dotted_name(node.func)
                    if dotted:
                        called.add(dotted.rsplit(".", 1)[-1])
                elif (isinstance(node, ast.Subscript)
                        and _dotted_name(node.value).endswith("environ")):
                    direct.add(fn.name)
            calls[fn.name] = called
        divergent = set(direct)
        changed = True
        while changed:
            changed = False
            for name, called in calls.items():
                if name not in divergent and called & divergent:
                    divergent.add(name)
                    changed = True
        return divergent

    def _check_function(self, rel: str, fn: ast.AST,
                        divergent_funcs: Set[str]) -> List[Finding]:
        own = _own_statements(fn)
        tainted, guarded = self._taint_names(own, divergent_funcs)
        findings: List[Finding] = []
        controls: List[ast.AST] = []

        def judge_call(node: ast.Call) -> None:
            dotted = _dotted_name(node.func)
            if not dotted or dotted.rsplit(".", 1)[-1] not in \
                    DISPATCH_MARKERS:
                return
            why = None
            for ctrl in controls:
                why = _contains_divergence(ctrl, tainted, divergent_funcs)
                if why:
                    break
            if not why:
                return
            if any(_contains_guard(c) for c in controls):
                return
            for ctrl in controls:
                for n in ast.walk(ctrl):
                    if isinstance(n, ast.Name) and n.id in guarded:
                        return
            findings.append(self.finding(
                rel, node.lineno,
                f"SPMD dispatch {dotted}() is controlled by per-process "
                f"state ({why}) — processes can diverge on whether/how "
                "often this collective runs",
                hint="make the controlling value process-uniform, or "
                     "gate the env-derived path with `not "
                     "mesh_spans_processes(mesh)` / `process_count() == "
                     "1` in the same condition (the PR 5 restore-chunk "
                     "idiom)", col=node.col_offset))

        def scan_exprs(node: ast.AST) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    judge_call(sub)

        def visit(stmts: Sequence[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.If):
                    scan_exprs(stmt.test)
                    controls.append(stmt.test)
                    visit(stmt.body)
                    visit(stmt.orelse)
                    controls.pop()
                elif isinstance(stmt, ast.While):
                    scan_exprs(stmt.test)
                    controls.append(stmt.test)
                    visit(stmt.body)
                    controls.pop()
                    visit(stmt.orelse)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_exprs(stmt.iter)
                    controls.append(stmt.iter)
                    visit(stmt.body)
                    controls.pop()
                    visit(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        scan_exprs(item.context_expr)
                    visit(stmt.body)
                elif isinstance(stmt, ast.Try):
                    visit(stmt.body)
                    for h in stmt.handlers:
                        visit(h.body)
                    visit(stmt.orelse)
                    visit(stmt.finalbody)
                else:
                    scan_exprs(stmt)

        visit(own)
        return findings

    def _taint_names(self, stmts: Sequence[ast.stmt],
                     divergent_funcs: Set[str]
                     ) -> Tuple[Set[str], Set[str]]:
        """(tainted, guarded): locals derived from per-process state,
        and the subset whose derivation ALSO consulted a topology guard
        (the sanctioned idiom — `pipelined = threads > 1 and not
        mesh_spans_processes(mesh)`)."""
        assigns: List[Tuple[List[str], ast.AST]] = []
        for node in _walk_own(stmts):
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if names and node.value is not None:
                    assigns.append((names, node.value))
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                t = node.target
                if isinstance(t, ast.Name) and node.value is not None:
                    assigns.append(([t.id], node.value))
        tainted: Set[str] = set()
        guarded: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for names, value in assigns:
                if _contains_divergence(value, tainted, divergent_funcs):
                    has_guard = (_contains_guard(value)
                                 or any(isinstance(n, ast.Name)
                                        and n.id in guarded
                                        for n in ast.walk(value)))
                    for n in names:
                        if n not in tainted:
                            tainted.add(n)
                            changed = True
                        if has_guard and n not in guarded:
                            guarded.add(n)
                            changed = True
        return tainted, guarded
