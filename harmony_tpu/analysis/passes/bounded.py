"""bounded-resource: server ingest paths must be capped.

The ``serve_tcp`` rule (caught in PR 17): the jobserver's accept loop
spawned one ``threading.Thread`` per connection — fine at ten tenants,
a fork bomb at a thousand-tenant submit storm (every connection costs a
stack, the scrape cycle starves, and the process wedges with no single
line at fault). The fix is structural: a fixed worker pool over a
BOUNDED queue, with admission control answering ``BUSY`` when it fills
(jobserver/overload.py). This pass keeps the unbounded shape from
creeping back in, in "server-shaped" code — any file that calls
``.accept()`` on a socket:

* a ``threading.Thread(...)`` constructed INSIDE a loop whose body also
  accepts connections: per-connection spawn, unbounded thread count
  under connection pressure;
* a ``queue.Queue()`` (or Lifo/Priority/SimpleQueue) constructed with
  no capacity in such a file: the pool may be fixed but its feed queue
  still grows without bound (``maxsize=0``/``None`` count as uncapped
  — that is what they mean);
* an accepted connection (a name bound from ``.accept()``) appended to
  a list/deque inside the accept loop: the hand-rolled variant of the
  uncapped queue.

Legitimately-bounded spawn sites (a replication peer set, a fixed
worker fleet) stay allowed via the standard pragma — with a written
reason stating WHAT bounds the connection count:
``# lint: allow(bounded-resource) <why the peer set is bounded>``.
"""
from __future__ import annotations

import ast
from typing import List, Set, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass, _dotted_name

#: queue constructors with (or, for SimpleQueue, without) a maxsize
_QUEUE_NAMES = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")


def _is_accept_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "accept")


def _uncapped_queue(node: ast.Call) -> bool:
    """True when this queue construction has no effective capacity.
    ``Queue(n)`` / ``Queue(maxsize=n)`` are capped unless n is the
    literal 0 or None (stdlib semantics: both mean infinite)."""
    last = (_dotted_name(node.func) or "").rsplit(".", 1)[-1]
    if last not in _QUEUE_NAMES:
        return False
    if last == "SimpleQueue":
        return True  # cannot be bounded at all
    cap = None
    if node.args:
        cap = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            cap = kw.value
    if cap is None:
        return True
    return (isinstance(cap, ast.Constant)
            and cap.value in (0, None))


def _conn_names(loop: ast.AST) -> Set[str]:
    """Names bound from an ``.accept()`` result inside the loop —
    ``conn, addr = sock.accept()`` binds both (an address list grows
    just as unboundedly as a connection list)."""
    out: Set[str] = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign) and _is_accept_call(node.value):
            for t in node.targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                out.update(e.id for e in elts if isinstance(e, ast.Name))
    return out


class BoundedResourcePass(Pass):
    name = "bounded-resource"
    description = ("server accept paths cap their resources: no "
                   "per-connection thread spawns, no uncapped ingest "
                   "queues or connection lists")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            if not any(_is_accept_call(n) for n in ast.walk(sf.tree)):
                continue  # not server-shaped: no accept loop here
            seen: Set[Tuple[str, int]] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and _uncapped_queue(node):
                    key = ("queue", node.lineno)
                    if key not in seen:
                        seen.add(key)
                        out.append(self.finding(
                            sf.rel, node.lineno,
                            "uncapped queue in server-shaped code: this "
                            "file accepts connections, and an ingest "
                            "queue with no maxsize grows without bound "
                            "under connection pressure",
                            hint="give it a capacity (`queue.Queue("
                                 "maxsize=cap)`) and shed work when "
                                 "full — the jobserver answers BUSY "
                                 "{retry_after_ms} (jobserver/"
                                 "overload.py)",
                            col=node.col_offset))
                if not isinstance(node, (ast.While, ast.For)):
                    continue
                if not any(_is_accept_call(n) for n in ast.walk(node)):
                    continue
                self._check_accept_loop(out, sf.rel, node, seen)
        return out

    def _check_accept_loop(self, out: List[Finding], rel: str,
                           loop: ast.AST,
                           seen: Set[Tuple[str, int]]) -> None:
        conns = _conn_names(loop)
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            last = (_dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if last == "Thread":
                key = ("thread", node.lineno)
                if key not in seen:
                    seen.add(key)
                    out.append(self.finding(
                        rel, node.lineno,
                        "per-connection thread spawn inside an accept "
                        "loop: thread count tracks connection count, "
                        "unbounded under a submit storm",
                        hint="use a fixed worker pool over a bounded "
                             "queue (the serve_tcp rule from PR 17); "
                             "if the peer set is genuinely bounded, "
                             "say why in a pragma",
                        col=node.col_offset))
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and any(isinstance(n, ast.Name) and n.id in conns
                            for a in node.args for n in ast.walk(a))):
                key = ("append", node.lineno)
                if key not in seen:
                    seen.add(key)
                    out.append(self.finding(
                        rel, node.lineno,
                        "accepted connection appended to an uncapped "
                        "list inside the accept loop: a hand-rolled "
                        "unbounded ingest queue",
                        hint="use a bounded queue.Queue and shed "
                             "(reply BUSY) on Full",
                        col=node.col_offset))
