"""thread-shared-state: shared mutable state holds its lock.

The ``_LEG_RETRIES`` rule (caught in PR 5 review): a counter mutated
from pool-submitted migration legs AND from the coordinating code,
where one side forgot the lock — increments interleave, retries vanish
from ``last_move_stats``, and the bug only reproduces under concurrent
legs on a loaded host. Statically checkable shape:

* a callable handed to a thread (``threading.Thread(target=...)``,
  ``pool.submit(f, ...)``, or this repo's pooled-leg helper
  ``_run_pooled(items, f, ...)``) mutates an attribute of its class or
  a module-level global, AND
* other (non-``__init__``) code of the same class/module mutates the
  same state, AND
* at least one of those mutation sites is not inside a ``with <lock>``
  block (any context-manager whose name contains lock/cond/mutex/sem).

Both sides must hold a lock — "the thread side is guarded" is not a
discipline, it is half of one. ``__init__`` (and ``__new__`` /
``__post_init__``) assignments are construction-time and exempt.
Methods reachable from a thread entry through ``self.<m>()`` calls, and
functions lexically nested inside thread callables, count as running on
the thread.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass, _dotted_name

# word-boundary-aware: `self._lock` / `_RETRY_LOCK` / `pod_cond` are
# locks; `block_writer` ('lock' mid-word) and `clock` are NOT
_LOCKISH = re.compile(r"(^|_)(lock|cond|mutex|sem|cv)s?($|_|\d)",
                      re.IGNORECASE)
_POOLED_HELPER = re.compile(r"(^|_)run_pooled$")
_INIT_METHODS = ("__init__", "__new__", "__post_init__")


def _lockish_with(node: ast.With) -> bool:
    for item in node.items:
        name = _dotted_name(item.context_expr)
        if not name and isinstance(item.context_expr, ast.Call):
            name = _dotted_name(item.context_expr.func)
        if name and _LOCKISH.search(name.rsplit(".", 1)[-1]):
            return True
    return False


@dataclasses.dataclass
class _MutSite:
    name: str              # attr or global name
    node: ast.AST
    func: ast.AST          # innermost def containing the mutation
    locked: bool
    in_init: bool
    where: str             # human label for the message


class _FileAnalysis(ast.NodeVisitor):
    """One walk: scope-resolved thread-entry targets, per-class attr
    mutations, module-global mutations — with the enclosing ``with``
    stack tracked for lock detection."""

    def __init__(self, tree: ast.Module) -> None:
        self.tree = tree
        # plain AND annotated assignments: the real `_LEG_RETRIES:
        # List[int] = [0]` is an AnnAssign — missing it would make this
        # pass blind to its own headline bug
        self.module_globals: Set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                self.module_globals.update(
                    t.id for t in stmt.targets if isinstance(t, ast.Name))
            elif (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                self.module_globals.add(stmt.target.id)
        #: def-node ids that are handed to a thread/pool directly
        self.thread_entries: Set[int] = set()
        #: (class-node id, method name) referenced as self.<m> targets
        self.thread_methods: Set[Tuple[int, str]] = set()
        #: class-node id -> {method name: def node}
        self.class_methods: Dict[int, Dict[str, ast.AST]] = {}
        #: class-node id -> class name
        self.class_names: Dict[int, str] = {}
        #: def-node id -> set of self.<m>() method names it calls
        self.self_calls: Dict[int, Set[str]] = {}
        #: def-node id -> id of the def it is lexically nested in
        self.parent_def: Dict[int, Optional[int]] = {}
        #: def-node id -> id of the enclosing class (methods AND defs
        #: nested inside them — a `self.<m>()` call from a nested leg
        #: function must resolve against the same class)
        self.def_class: Dict[int, Optional[int]] = {}
        #: def-node id -> def node
        self.defs: Dict[int, ast.AST] = {}
        #: mutations of self.<attr>: class-node id -> list[_MutSite]
        self.attr_muts: Dict[int, List[_MutSite]] = {}
        #: mutations of module globals: name -> list[_MutSite]
        self.global_muts: Dict[str, List[_MutSite]] = {}

        self._scopes: List[Dict[str, ast.AST]] = []
        self._class_stack: List[int] = []
        self._def_stack: List[ast.AST] = []
        self._with_locks = 0
        self._visit_module()

    # -- scope plumbing ---------------------------------------------------

    def _visit_module(self) -> None:
        self._scopes.append(self._defs_in(self.tree.body))
        for stmt in self.tree.body:
            self.visit(stmt)

    @staticmethod
    def _defs_in(body: List[ast.stmt]) -> Dict[str, ast.AST]:
        return {s.name: s for s in body
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _resolve(self, name: str) -> Optional[ast.AST]:
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_methods[id(node)] = self._defs_in(node.body)
        self.class_names[id(node)] = node.name
        self._class_stack.append(id(node))
        self._scopes.append({})  # class body is not a name scope for defs
        for stmt in node.body:
            self.visit(stmt)
        self._scopes.pop()
        self._class_stack.pop()

    def _visit_def(self, node) -> None:
        self.defs[id(node)] = node
        self.parent_def[id(node)] = (
            id(self._def_stack[-1]) if self._def_stack else None)
        self.def_class[id(node)] = (
            self._class_stack[-1] if self._class_stack else None)
        self._def_stack.append(node)
        self._scopes.append(self._defs_in(node.body))
        saved_locks = self._with_locks
        self._with_locks = 0  # a lock held OUTSIDE a def does not guard
        for stmt in node.body:  # a deferred call of the def
            self.visit(stmt)
        self._with_locks = saved_locks
        self._scopes.pop()
        self._def_stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_With(self, node: ast.With) -> None:
        locked = _lockish_with(node)
        for item in node.items:
            self.visit(item.context_expr)
        if locked:
            self._with_locks += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self._with_locks -= 1

    visit_AsyncWith = visit_With

    # -- thread-entry discovery ------------------------------------------

    def _mark_entry(self, expr: ast.AST) -> None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self._class_stack):
            self.thread_methods.add((self._class_stack[-1], expr.attr))
        elif isinstance(expr, ast.Name):
            target = self._resolve(expr.id)
            if target is not None:
                self.thread_entries.add(id(target))

    def visit_Call(self, node: ast.Call) -> None:
        fname = _dotted_name(node.func)
        last = fname.rsplit(".", 1)[-1] if fname else ""
        if last == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self._mark_entry(kw.value)
        elif last == "submit" and node.args:
            self._mark_entry(node.args[0])
        elif _POOLED_HELPER.search(last):
            for arg in node.args:
                self._mark_entry(arg)
        # self.<m>() calls, for the runs-on-thread closure
        if (self._def_stack
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            self.self_calls.setdefault(
                id(self._def_stack[-1]), set()).add(node.func.attr)
        self.generic_visit(node)

    # -- mutation discovery ----------------------------------------------

    def _record_mutation(self, target: ast.AST, stmt: ast.AST) -> None:
        if not self._def_stack:
            return  # module-level execution is import-time, single-threaded
        base = target
        while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            base = base.value
        func = self._def_stack[-1]
        fname = getattr(func, "name", "<lambda>")
        locked = self._with_locks > 0
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and self._class_stack):
            self.attr_muts.setdefault(self._class_stack[-1], []).append(
                _MutSite(name=base.attr, node=stmt, func=func,
                         locked=locked, in_init=fname in _INIT_METHODS,
                         where=fname))
        elif isinstance(base, ast.Name) and base.id in self.module_globals:
            is_rebind = base is target  # plain `X = ...` needs `global`
            if is_rebind and not self._has_global_decl(base.id):
                return
            self.global_muts.setdefault(base.id, []).append(
                _MutSite(name=base.id, node=stmt, func=func,
                         locked=locked, in_init=False, where=fname))

    def _has_global_decl(self, name: str) -> bool:
        for func in self._def_stack:
            for stmt in ast.walk(func):
                if isinstance(stmt, ast.Global) and name in stmt.names:
                    return True
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_mutation(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_mutation(node.target, node)
        self.generic_visit(node)

    # -- runs-on-thread closure ------------------------------------------

    def thread_ctx(self) -> Set[int]:
        ctx: Set[int] = set(self.thread_entries)
        for cls_id, mname in self.thread_methods:
            m = self.class_methods.get(cls_id, {}).get(mname)
            if m is not None:
                ctx.add(id(m))
        changed = True
        while changed:
            changed = False
            # self.<m>() from ANY def running on the thread — a method
            # or a def lexically nested inside one (the closure-heavy
            # leg-function shape) — puts the callee on the thread
            for def_id in list(ctx):
                cls_id = self.def_class.get(def_id)
                if cls_id is None:
                    continue
                methods = self.class_methods.get(cls_id, {})
                for callee in self.self_calls.get(def_id, ()):
                    c = methods.get(callee)
                    if c is not None and id(c) not in ctx:
                        ctx.add(id(c))
                        changed = True
            # defs nested inside a thread callable run on the thread
            for def_id, parent in self.parent_def.items():
                if (def_id not in ctx and parent is not None
                        and parent in ctx):
                    ctx.add(def_id)
                    changed = True
        return ctx


class ThreadSharedStatePass(Pass):
    name = "thread-shared-state"
    description = ("state mutated from a thread/pool callable and from "
                   "other code of the same class/module holds a common "
                   "lock on both sides")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            fa = _FileAnalysis(sf.tree)
            ctx = fa.thread_ctx()

            def runs_on_thread(site: _MutSite) -> bool:
                return id(site.func) in ctx

            for cls_id, sites in fa.attr_muts.items():
                cname = fa.class_names.get(cls_id, "?")
                by_attr: Dict[str, List[_MutSite]] = {}
                for s in sites:
                    by_attr.setdefault(s.name, []).append(s)
                for attr, group in by_attr.items():
                    self._judge(out, sf.rel, f"{cname}.{attr}", group,
                                runs_on_thread)
            for gname, group in fa.global_muts.items():
                self._judge(out, sf.rel, gname, group, runs_on_thread)
        return out

    def _judge(self, out: List[Finding], rel: str, label: str,
               group: List[_MutSite], runs_on_thread) -> None:
        thread_side = [s for s in group if runs_on_thread(s)]
        other_side = [s for s in group
                      if not runs_on_thread(s) and not s.in_init]
        if not thread_side or not other_side:
            return
        unguarded = [s for s in thread_side + other_side if not s.locked]
        for s in unguarded:
            side = ("a thread/pool callable" if runs_on_thread(s)
                    else "non-thread code")
            out.append(self.finding(
                rel, s.node.lineno,
                f"{label} is mutated from {side} ({s.where}) without its "
                "lock, while the other side of the same state also "
                "mutates it",
                hint="hold one lock at EVERY mutation site (`with "
                     "self._lock:` / the module's lock) — the "
                     "_LEG_RETRIES rule from PR 5",
                col=getattr(s.node, "col_offset", 0)))
