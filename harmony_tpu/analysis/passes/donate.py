"""use-after-donate: a donated buffer is dead after the jitted call.

The fused hot path's memory contract (docs/DEVICE_HOT_PATH.md): a
buffer passed in a ``donate_argnums`` position of a jitted call is
handed to XLA, which reuses its memory for outputs — touching the old
handle afterwards is undefined (jax surfaces it as a
"donated buffer was deleted" error at best, silent garbage under
async dispatch at worst). PR 6's donation tests pin this dynamically
for the shipped steps; this pass pins the pattern statically wherever a
wrapper's donation positions are visible:

* ``w = jax.jit(fn, donate_argnums=(0,))`` (module- or function-scope;
  ``@functools.partial(jax.jit, donate_argnums=...)`` defs too), then
* ``w(tbl, batch)`` followed by a read of ``tbl`` in the same scope
  with no rebinding in between → finding at the read;
* ``w(tbl, batch)`` inside a loop with no rebinding of ``tbl`` anywhere
  in that loop → finding at the call (the next iteration re-donates a
  dead buffer). ``tbl = w(tbl, batch)`` is the sanctioned shape.

Ping/pong double-buffer rotation (the async step's overlap window,
docs/DEVICE_HOT_PATH.md §Async step mode) is understood: a pure-name
tuple assignment like ``ping, pong = pong, ping`` MOVES handles — the
RHS names are handle copies, not device reads, so the rotation itself
never fires a finding, and a donated name whose handle rotates onto a
new name counts as rebound for the loop rule. The deadness follows the
handle instead: after the rotation the ALIAS now holding the donated
buffer is tracked, and a read of it inside the overlap window without a
rebinding fence (``view = drv.wait_view()``-style republish) is flagged
at the read.

Reads inside nested functions are deferred calls the linear scan cannot
order and are out of scope (the dynamic donation tests own those).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from harmony_tpu.analysis.core import (
    CodebaseIndex,
    Finding,
    Pass,
    _dotted_name,
    is_jit_call as _is_jit_func,
)


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums of a jax.jit(...) call (None when absent
    or not statically known)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)):
                    return None
            return tuple(el.value for el in v.elts)
    return None


# event kinds, in execution order within a scope
_DONATE, _STORE, _LOAD = "donate", "store", "load"
#: one pure-name tuple assignment (``a, b = b, a``): handles MOVE
#: atomically (every RHS read precedes every LHS bind), so the whole
#: rotation is ONE event carrying its dst<-src mapping
_MOVE = "move"


class _ScopeScanner:
    """Collects (kind, name, node, loop_stack) events for one scope in
    execution order (values before targets), without descending into
    nested function/class scopes."""

    def __init__(self, wrappers: Dict[str, Tuple[int, ...]]) -> None:
        self.wrappers = dict(wrappers)
        #: (kind, name, node, loop-stack, branch-path, moves); branch-
        #: path is ((if-node-id, arm), ...) so the judge can recognize
        #: mutually exclusive if/else arms and not order them against
        #: each other; moves is the ((dst, src), ...) mapping of a _MOVE
        #: event (empty for every other kind)
        self.events: List[
            Tuple[str, str, ast.AST, Tuple[int, ...],
                  Tuple[Tuple[int, int], ...],
                  Tuple[Tuple[str, str], ...]]] = []
        self._loops: List[int] = []
        self._branches: List[Tuple[int, int]] = []

    def scan(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _emit(self, kind: str, name: str, node: ast.AST,
              moves: Tuple[Tuple[str, str], ...] = ()) -> None:
        self.events.append((kind, name, node, tuple(self._loops),
                            tuple(self._branches), moves))

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested scope: its deferred execution cannot be ordered
            # against this scope's events — skipped (module docstring)
            for dec in getattr(node, "decorator_list", ()):
                self._expr(dec)
            self._emit(_STORE, node.name, node)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is not None:
                # wrapper definition?
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                if (isinstance(value, ast.Call) and _is_jit_func(value.func)
                        and len(targets) == 1
                        and isinstance(targets[0], ast.Name)):
                    pos = _donate_positions(value)
                    if pos:
                        self.wrappers[targets[0].id] = pos
                # ping/pong rotation: a pure-name tuple assignment moves
                # handles without touching device memory — ONE atomic
                # _MOVE event instead of loads+stores (module docstring)
                if (isinstance(value, ast.Tuple)
                        and len(targets) == 1
                        and isinstance(targets[0], ast.Tuple)
                        and len(value.elts) == len(targets[0].elts) > 1
                        and all(isinstance(e, ast.Name)
                                for e in value.elts)
                        and all(isinstance(e, ast.Name)
                                for e in targets[0].elts)):
                    self._emit(_MOVE, "", node, moves=tuple(
                        (dst.id, src.id)
                        for dst, src in zip(targets[0].elts, value.elts)))
                    return
                self._expr(value)
                for t in targets:
                    self._target(t)
            return
        if isinstance(node, ast.AugAssign):
            self._expr(node.value)
            name = _dotted_name(node.target)
            if name:
                self._emit(_LOAD, name, node.target)
                self._emit(_STORE, name, node.target)
            else:
                self._expr(node.target)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                name = _dotted_name(t)
                if name:
                    self._emit(_STORE, name, t)  # the handle is gone
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter)
            self._loops.append(id(node))
            self._target(node.target)
            for s in node.body:
                self._stmt(s)
            self._loops.pop()
            for s in node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.While):
            self._loops.append(id(node))
            self._expr(node.test)
            for s in node.body:
                self._stmt(s)
            self._loops.pop()
            for s in node.orelse:
                self._stmt(s)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._target(item.optional_vars)
            for s in node.body:
                self._stmt(s)
            return
        if isinstance(node, ast.If):
            self._expr(node.test)
            self._branches.append((id(node), 0))
            for s in node.body:
                self._stmt(s)
            self._branches[-1] = (id(node), 1)
            for s in node.orelse:
                self._stmt(s)
            self._branches.pop()
            return
        if isinstance(node, ast.Try):
            for s in node.body:
                self._stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
            for s in node.finalbody:
                self._stmt(s)
            return
        # Expr / Return / Raise / Assert / everything else: scan values
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _target(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                self._target(el)
            return
        if isinstance(node, ast.Starred):
            self._target(node.value)
            return
        name = _dotted_name(node)
        if name:
            self._emit(_STORE, name, node)
        else:
            # subscript targets etc: the base is LOADED (x[i] = v reads x)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)

    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            if fname is not None and fname in self.wrappers:
                self._expr_children_of_call(node, self.wrappers[fname])
                return
        if isinstance(node, (ast.Lambda,)):
            return  # deferred
        name = _dotted_name(node)
        if name and isinstance(node, (ast.Name, ast.Attribute)):
            self._emit(_LOAD, name, node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.keyword):
                self._expr(child.value)
            elif isinstance(child, ast.comprehension):
                self._expr(child.iter)
                for c in child.ifs:
                    self._expr(c)

    def _expr_children_of_call(self, node: ast.Call,
                               positions: Tuple[int, ...]) -> None:
        for i, arg in enumerate(node.args):
            name = _dotted_name(arg)
            if i in positions and name:
                self._emit(_DONATE, name, arg)
            else:
                self._expr(arg)
        for kw in node.keywords:
            self._expr(kw.value)


class UseAfterDonatePass(Pass):
    name = "use-after-donate"
    description = ("a name passed in a donate_argnums position is not "
                   "read again before rebinding")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            # module-level wrappers are visible inside functions
            module_wrappers: Dict[str, Tuple[int, ...]] = {}
            for node in sf.tree.body:
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _is_jit_func(node.value.func)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    pos = _donate_positions(node.value)
                    if pos:
                        module_wrappers[node.targets[0].id] = pos
            # @functools.partial(jax.jit, donate_argnums=...) defs donate
            # their own params; register them ALL before snapshotting any
            # scope — a caller defined earlier in the file than the
            # decorated step must still see the donation
            for node in ast.walk(sf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    if (isinstance(dec, ast.Call)
                            and _dotted_name(dec.func).endswith("partial")
                            and dec.args
                            and _is_jit_func(dec.args[0])):
                        pos = _donate_positions(dec)
                        if pos:
                            module_wrappers[node.name] = pos
            scopes: List[Tuple[List[ast.stmt], Dict[str, Tuple[int, ...]]]]
            scopes = [(sf.tree.body, module_wrappers)]
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scopes.append((node.body, dict(module_wrappers)))
            for body, wrappers in scopes:
                sc = _ScopeScanner(wrappers)
                sc.scan(body)
                out.extend(self._judge(sf.rel, sc.events))
        return out

    @staticmethod
    def _exclusive(a: Tuple[Tuple[int, int], ...],
                   b: Tuple[Tuple[int, int], ...]) -> bool:
        """True when two events sit in different arms of the same
        ``if`` — only one of them executes, so neither orders against
        the other."""
        arms_a = dict(a)
        return any(if_id in arms_a and arms_a[if_id] != arm
                   for if_id, arm in b)

    def _judge(self, rel: str, events) -> List[Finding]:
        out: List[Finding] = []
        for i, (kind, name, node, loops, branches, _mv) in enumerate(events):
            if kind != _DONATE:
                continue
            # `cur` tracks the NAME currently holding the donated (dead)
            # handle — a ping/pong rotation moves the deadness to the
            # alias instead of killing the scan
            cur = name
            for kind2, name2, node2, _loops2, branches2, mv2 in (
                    events[i + 1:]):
                if self._exclusive(branches, branches2):
                    continue  # sibling if/else arm: never both execute
                if kind2 == _MOVE:
                    dst_of = {src: dst for dst, src in mv2}
                    if cur in dst_of:
                        # the dead handle rotated: follow it. (If cur is
                        # also a move TARGET — the swap case — the handle
                        # still leaves; the fresh handle landing on cur
                        # is the rebind the loop rule credits.)
                        cur = dst_of[cur]
                        continue
                    if any(dst == cur for dst, _src in mv2):
                        break  # cur rebound to some other live handle
                    continue
                # tbl.sum() / tbl[k] reads are reads of tbl; only a
                # store of the NAME itself rebinds it
                if name2 != cur and not name2.startswith(cur + "."):
                    continue
                if kind2 == _STORE and name2 == cur:
                    break
                if kind2 == _STORE:
                    continue
                alias = ("" if cur == name else
                         f" (the handle rotated onto {cur!r} without a "
                         "rebinding fence)")
                # message stays line-free (Finding.key() is the baseline
                # identity); the donate site is recoverable from the hint
                out.append(self.finding(
                    rel, node2.lineno,
                    f"{name!r} was donated to a jitted call earlier in "
                    f"this scope and is read here without rebinding"
                    + alias,
                    hint="a donated buffer is dead after the step — "
                         "bind the call's result (`x = step(x, ...)`) "
                         "or stop donating this argument",
                    col=node2.col_offset))
                break
            if loops:
                def rebinds(e) -> bool:
                    if e[0] == _STORE and e[1] == name:
                        return True
                    # a move landing on the donated name gives it a new
                    # handle — the rotation's sanctioned rebind
                    return e[0] == _MOVE and any(
                        dst == name for dst, _src in e[5])

                in_loop = [e for e in events if e[3][:len(loops)] == loops]
                if not any(rebinds(e) for e in in_loop):
                    out.append(self.finding(
                        rel, node.lineno,
                        f"{name!r} is donated inside a loop but never "
                        "rebound in it — the next iteration re-donates "
                        "a dead buffer",
                        hint="bind the result back (`x = step(x, ...)`) "
                             "so each iteration donates a live buffer",
                        col=node.col_offset))
        return out
