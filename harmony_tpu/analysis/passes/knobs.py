"""knob-consistency: HARMONY_* env knobs ⇄ docs ⇄ deploy manifests.

Generalizes (and supersedes) the one-off env/doc check that lived in
tests/test_gke_manifests.py. Three directions:

1. every ``HARMONY_*`` env READ in code appears in a docs/*.md knob
   table — an undocumented knob is configuration operators cannot
   discover (the DEPLOY/FAULT_TOLERANCE/OBSERVABILITY/DEVICE_HOT_PATH
   tables are the operator surface);
2. every ``HARMONY_*`` variable a deploy/gke manifest wires is actually
   read somewhere in the repo — a manifest env nobody reads is dead
   configuration that LOOKS load-bearing;
3. every manifest-wired knob is documented (the original
   test_gke_manifests rule).

Prefix reads — ``"HARMONY_RETRY_" + field.upper()`` in
config/params.py — are honored: a literal ending in ``_`` counts as
covering every knob it prefixes.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from harmony_tpu.analysis.core import (
    CodebaseIndex,
    Finding,
    Pass,
    _dotted_name as _dotted,
)

_KNOB_RE = re.compile(r"HARMONY_[A-Z0-9_]+")
_MANIFEST_ENV_RE = re.compile(r"-\s*name:\s*(HARMONY_[A-Z0-9_]+)")
_ENVISH_CALL = re.compile(r"(^|\.)(environ|getenv|env_[a-z_]+)($|\.)")

#: The operator surface: knob TABLE ROWS in these docs are what counts
#: as documentation. A knob name-dropped in prose — or in
#: STATIC_ANALYSIS.md's own bug anecdotes — gives operators no
#: name/default/meaning row and must NOT satisfy this pass.
_OPERATOR_DOCS = ("DEPLOY.md", "FAULT_TOLERANCE.md", "OBSERVABILITY.md",
                  "DEVICE_HOT_PATH.md", "INPUT_PIPELINE.md")


def _documented_knobs(index: CodebaseIndex) -> Set[str]:
    out: Set[str] = set()
    for name in _OPERATOR_DOCS:
        for line in index.doc_text(name).splitlines():
            if line.lstrip().startswith("|"):
                out.update(_KNOB_RE.findall(line))
    return out


def _reads_in_tree(tree: ast.AST, rel: str) -> List[Tuple[str, str, int]]:
    """(knob_or_prefix, file, line) for every HARMONY_* literal that is
    part of an environment READ: inside a call whose function name looks
    env-ish (os.environ.get / os.getenv / env_choice / ...), or a
    subscript of ``os.environ``. A knob name in a comment or docstring
    is NOT a read — that distinction is what makes the 'manifest knob
    read nowhere' direction mean something. Module-level constants
    (``ENV_PORT = "HARMONY_METRICS_PORT"`` ... ``environ.get(ENV_PORT)``,
    the exporter/flight idiom) resolve through one level."""
    consts: dict = {}
    body = getattr(tree, "body", [])
    for stmt in body:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target] if isinstance(stmt, ast.AnnAssign)
                   else [])
        v = getattr(stmt, "value", None)
        if (isinstance(v, ast.Constant) and isinstance(v.value, str)
                and v.value.startswith("HARMONY_")):
            for t in targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = v.value
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        holders: List[ast.AST] = []
        if isinstance(node, ast.Call) and _ENVISH_CALL.search(
                _dotted(node.func)):
            holders = list(node.args)
        elif (isinstance(node, ast.Subscript)
                and _dotted(node.value).endswith("environ")):
            holders = [node.slice]
        for h in holders:
            for sub in ast.walk(h):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                        and sub.value.startswith("HARMONY_")):
                    out.append((sub.value, rel, node.lineno))
                elif isinstance(sub, ast.Name) and sub.id in consts:
                    out.append((consts[sub.id], rel, node.lineno))
    return out


def _read_literals(index: CodebaseIndex) -> List[Tuple[str, str, int]]:
    out: List[Tuple[str, str, int]] = []
    for sf in index.files:
        if sf.tree is not None:
            out.extend(_reads_in_tree(sf.tree, sf.rel))
    return out


def _read_fodder(tree: ast.AST) -> Set[str]:
    """Knob-shaped string constants anywhere in the AST EXCEPT
    docstrings — name tables like RetryPolicy._ENV_FIELDS (full names
    read via ``os.environ.get(var)`` in a loop) and ``"HARMONY_X_" +
    field.upper()`` prefix builds. Used ONLY to answer 'is this
    manifest knob read somewhere' (direction 2): looser than
    :func:`_reads_in_tree` but still excludes prose, since comments
    never parse and docstrings are skipped here."""
    docstrings = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                docstrings.add(id(body[0].value))
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and node.value.startswith("HARMONY_")
                and id(node) not in docstrings):
            out.update(_KNOB_RE.findall(node.value))
            if node.value.endswith("_"):
                out.add(node.value)
    return out


def _covered(knob: str, reads: Set[str]) -> bool:
    if knob in reads:
        return True
    return any(r.endswith("_") and knob.startswith(r) for r in reads)


class KnobConsistencyPass(Pass):
    name = "knob-consistency"
    description = ("HARMONY_* knobs read in code are documented, and "
                   "every manifest-wired knob is read and documented")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        documented = _documented_knobs(index)

        reads = _read_literals(index)
        if not documented:
            # no operator knob tables resolvable (docs/ absent — e.g. a
            # site-packages install): one structural finding, not one
            # per read
            if reads:
                out.append(self.finding(
                    "docs/DEPLOY.md", 1,
                    "no operator knob tables found under docs/ "
                    f"({'/'.join(_OPERATOR_DOCS)})",
                    hint="run the lint from the repo root (the knob "
                         "tables are the operator contract this pass "
                         "checks against)"))
            return out
        for knob, file, line in reads:
            if knob.endswith("_"):
                continue  # prefix read; concrete names come from fields
            if knob not in documented:
                out.append(self.finding(
                    file, line,
                    f"env knob {knob} is read here but documented in no "
                    "docs/*.md knob table",
                    hint="add a row (name / default / meaning) to the "
                         "DEPLOY knob table — undocumented knobs are "
                         "how deployments drift from their operators"))

        if index.partial:
            # a file slice cannot prove a manifest knob is read nowhere
            return out

        # direction 2+3 need the WIDER read surface (tests/benchmarks
        # legitimately read bench-only knobs like HARMONY_POD_UNIT_LAT_MS)
        # — still as AST-level READS; a file that does not parse falls
        # back to a raw-text scan rather than marking its knobs unread
        read_names: Set[str] = {k for k, _, _ in reads}
        for sf in index.files:
            if sf.tree is not None:
                read_names.update(_read_fodder(sf.tree))
        scanned = {sf.rel for sf in index.files}
        for rel, text in index.repo_py_texts().items():
            if rel in scanned:
                continue
            try:
                tree = ast.parse(text)
            except (SyntaxError, ValueError):
                read_names.update(_KNOB_RE.findall(text))
                continue
            read_names.update(k for k, _, _ in _reads_in_tree(tree, rel))
            read_names.update(_read_fodder(tree))

        for rel, text in sorted(index.deploy_manifests().items()):
            lines = text.splitlines()
            wired: Dict[str, int] = {}
            for lno, line in enumerate(lines, start=1):
                m = _MANIFEST_ENV_RE.search(line)
                if m:
                    wired[m.group(1)] = lno
            for knob, lno in sorted(wired.items()):
                if not _covered(knob, read_names):
                    out.append(self.finding(
                        rel, lno,
                        f"manifest wires {knob} but nothing in the repo "
                        "reads it",
                        hint="dead env looks load-bearing to operators; "
                             "drop it or wire the read"))
                if knob not in documented:
                    out.append(self.finding(
                        rel, lno,
                        f"manifest wires {knob} but no docs/*.md "
                        "documents it",
                        hint="the DEPLOY knob table is the operator "
                             "contract for deploy artifacts"))
        return out
