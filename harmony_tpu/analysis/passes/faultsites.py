"""fault-site-registry: code fault sites ⇄ docs/FAULT_TOLERANCE.md.

The chaos harness (PR 2) addresses faults by NAME: a plan rule armed at
``"chkp.block_write"`` only ever fires if production code actually
declares ``faults.site("chkp.block_write", ...)``. A typo'd or stale
site name fails silently — the chaos test "passes" while injecting
nothing, which is worse than no test. Both directions are pinned
against the registry table in docs/FAULT_TOLERANCE.md (§Fault-site
registry):

* every site literal fired in code has a registry row (operators pick
  injection points from that table; an unlisted site is invisible
  chaos surface),
* every registry row is fired somewhere in code (a dead row arms plans
  that can never trip — the silent-pass failure mode above).

Site names inside ``"a.b" if cond else "c.d"`` selector expressions are
all collected.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass

REGISTRY_DOC = "FAULT_TOLERANCE.md"
_SECTION = "### Fault-site registry"
_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|")
_SITE_SHAPE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


def _doc_registry(text: str) -> Dict[str, int]:
    """site -> 1-based line number of its registry row."""
    sites: Dict[str, int] = {}
    in_section = False
    for lno, line in enumerate(text.splitlines(), start=1):
        if line.strip() == _SECTION:
            in_section = True
            continue
        if in_section and line.startswith(("## ", "### ")):
            break
        if in_section:
            m = _ROW_RE.match(line.strip())
            if m:
                sites[m.group(1)] = lno
    return sites


def _code_sites(index: CodebaseIndex) -> List[Tuple[str, str, int]]:
    """(site, file, line) for every literal inside the first argument of
    a ``faults.site(...)`` call."""
    out: List[Tuple[str, str, int]] = []
    for sf in index.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            f = node.func
            is_site = (
                isinstance(f, ast.Attribute) and f.attr == "site"
                and isinstance(f.value, ast.Name) and f.value.id == "faults")
            if not is_site:
                continue
            for sub in ast.walk(node.args[0]):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                        and _SITE_SHAPE.match(sub.value)):
                    out.append((sub.value, sf.rel, node.lineno))
    return out


class FaultSiteRegistryPass(Pass):
    name = "fault-site-registry"
    description = ("every faults.site() name has a FAULT_TOLERANCE.md "
                   "registry row and every row is fired in code")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        doc_rel = f"docs/{REGISTRY_DOC}"
        text = index.doc_text(REGISTRY_DOC)
        registry = _doc_registry(text)
        fired = _code_sites(index)
        if not text or not registry:
            if fired:  # fixture trees without chaos sites need no doc
                out.append(self.finding(
                    doc_rel, 1,
                    "fault-site registry table not found "
                    f"({_SECTION} in {doc_rel})",
                    hint="the chaos harness's site names are operator "
                         "API; the registry table is their source of "
                         "truth"))
            return out
        fired_names = {s for s, _, _ in fired}
        for site, file, line in fired:
            if site not in registry:
                out.append(self.finding(
                    file, line,
                    f"fault site {site!r} is not in the {doc_rel} "
                    "registry",
                    hint="add a row (site / layer / context keys) — or "
                         "this is a typo'd site no plan can ever arm"))
        for site, lno in sorted(registry.items()):
            if index.partial:
                break  # a file slice cannot prove a site is unfired
            if site not in fired_names:
                out.append(self.finding(
                    doc_rel, lno,
                    f"registry row {site!r} has no faults.site() in "
                    "code",
                    hint="a dead row arms chaos plans that silently "
                         "never trip; drop the row or restore the site"))
        return out
