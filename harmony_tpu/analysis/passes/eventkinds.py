"""event-kind-registry: emitted joblog kinds ⇄ catalog ⇄ docs.

The structured event stream (jobserver/joblog.py) is addressed by
``kind=`` literals declared ad hoc across ~15 modules, and two consumers
now dispatch on those names: the incident engine's role classification
(metrics/incidents.py) and operators grepping OBSERVABILITY.md. A typo'd
or undeclared kind fails silently — the event records fine, correlates
as nothing, and appears in no table. Three directions are pinned against
the declared catalog (``EVENT_KINDS`` in jobserver/joblog.py, the
doctor_rule precedent applied to the stream itself):

* every literal kind emitted in code (``record_event(...)``, a
  ``.event("...")`` recorder call, ``_record_pod_event("...")``) is
  declared in the catalog,
* every catalog entry has a row in the OBSERVABILITY.md event-kind
  table (§Event-kind registry),
* every table row is a catalog entry (a dead row documents events that
  can never appear).

Dynamic kinds (the ``elastic_{kind}`` f-strings in jobserver/pod.py)
cannot be collected statically, so the catalog declares each expansion
and the "every catalog entry is emitted somewhere" direction is
deliberately NOT enforced — it would be unanswerable.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass

REGISTRY_DOC = "OBSERVABILITY.md"
_SECTION = "### Event-kind registry"
_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
_KIND_SHAPE = re.compile(r"^[a-z][a-z0-9_]*$")
_CATALOG_NAME = "EVENT_KINDS"


def _doc_rows(text: str) -> Dict[str, int]:
    """kind -> 1-based line number of its event-kind table row."""
    rows: Dict[str, int] = {}
    in_section = False
    for lno, line in enumerate(text.splitlines(), start=1):
        if line.strip() == _SECTION:
            in_section = True
            continue
        if in_section and line.startswith(("## ", "### ")):
            break
        if in_section:
            m = _ROW_RE.match(line.strip())
            if m:
                rows[m.group(1)] = lno
    return rows


def _catalog(index: CodebaseIndex) -> Dict[str, Tuple[str, int]]:
    """kind -> (file, line) from the ``EVENT_KINDS`` dict literal."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in index.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):  # EVENT_KINDS: Dict = {}
                targets = [node.target]
            else:
                continue
            if not any(isinstance(t, ast.Name) and t.id == _CATALOG_NAME
                       for t in targets):
                continue
            if not isinstance(node.value, ast.Dict):
                continue
            for key in node.value.keys:
                if (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    out[key.value] = (sf.rel, key.lineno)
    return out


def _emitted(index: CodebaseIndex) -> List[Tuple[str, str, int]]:
    """(kind, file, line) for every literal event kind an emit call
    names: ``record_event(job, "kind", ...)`` (positional or
    ``kind="..."``), ``<recorder>.event("kind", ...)``, and
    ``_record_pod_event("kind", ...)``."""
    out: List[Tuple[str, str, int]] = []

    def _const(node) -> str:
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and _KIND_SHAPE.match(node.value)):
            return node.value
        return ""

    for sf in index.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = (f.attr if isinstance(f, ast.Attribute)
                     else f.id if isinstance(f, ast.Name) else "")
            kind = ""
            if fname in ("record_event", "_record_pod_event"):
                idx = 1 if fname == "record_event" else 0
                if len(node.args) > idx:
                    kind = _const(node.args[idx])
                for kw in node.keywords:
                    if kw.arg == "kind":
                        kind = _const(kw.value)
            elif fname == "event" and isinstance(f, ast.Attribute):
                if node.args:
                    kind = _const(node.args[0])
            if kind:
                out.append((kind, sf.rel, node.lineno))
    return out


class EventKindRegistryPass(Pass):
    name = "event-kind-registry"
    description = ("every emitted joblog event kind is declared in "
                   "joblog.EVENT_KINDS and tabled in OBSERVABILITY.md")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        doc_rel = f"docs/{REGISTRY_DOC}"
        catalog = _catalog(index)
        emits = _emitted(index)
        if not catalog:
            if emits and not index.partial:
                kind, file, line = emits[0]
                out.append(self.finding(
                    file, line,
                    f"event kind {kind!r} emitted but no "
                    f"{_CATALOG_NAME} catalog exists",
                    hint="declare the catalog dict (jobserver/joblog.py "
                         "precedent); undeclared kinds are invisible to "
                         "incident correlation"))
            return out
        for kind, file, line in emits:
            if kind not in catalog:
                out.append(self.finding(
                    file, line,
                    f"event kind {kind!r} is not declared in "
                    f"{_CATALOG_NAME}",
                    hint="add a catalog entry (+ the OBSERVABILITY.md "
                         "row) — or this is a typo no consumer will "
                         "ever match"))
        if index.partial:
            return out  # a file slice cannot prove doc parity
        rows = _doc_rows(index.doc_text(REGISTRY_DOC))
        if not rows:
            cat_file, cat_line = next(iter(sorted(catalog.values())))
            out.append(self.finding(
                cat_file, cat_line,
                f"event-kind table not found ({_SECTION} in {doc_rel})",
                hint="the catalog is operator API; its table is the "
                     "documented source of truth"))
            return out
        for kind, (file, line) in sorted(catalog.items()):
            if kind not in rows:
                out.append(self.finding(
                    file, line,
                    f"catalog kind {kind!r} has no {doc_rel} "
                    "event-kind row",
                    hint="add a row (kind / emitter / meaning) to the "
                         f"{_SECTION} table"))
        for kind, lno in sorted(rows.items()):
            if kind not in catalog:
                out.append(self.finding(
                    doc_rel, lno,
                    f"event-kind row {kind!r} is not declared in "
                    f"{_CATALOG_NAME}",
                    hint="a dead row documents events that can never "
                         "appear; drop the row or declare the kind"))
        return out
