"""Pass registry. Each module holds one pass; the order here is the
report order (concurrency/correctness passes first, conventions last).

Adding a pass (docs/STATIC_ANALYSIS.md has the full walkthrough):

1. new module with a :class:`harmony_tpu.analysis.core.Pass` subclass,
2. register the class in ``_REGISTRY``,
3. a bad/fixed fixture pair under ``tests/fixtures/lint/`` plus a case
   in ``tests/test_analysis.py::TestPassFixtures``,
4. run ``bin/lint.sh`` — the new pass must come up green on the real
   tree (fix what it finds; allowlist only with a written reason).
"""
from __future__ import annotations

from typing import List

from harmony_tpu.analysis.core import Pass, PragmaHygienePass
from harmony_tpu.analysis.passes.bounded import BoundedResourcePass
from harmony_tpu.analysis.passes.donate import UseAfterDonatePass
from harmony_tpu.analysis.passes.eventkinds import EventKindRegistryPass
from harmony_tpu.analysis.passes.faultsites import FaultSiteRegistryPass
from harmony_tpu.analysis.passes.jit import JitHygienePass
from harmony_tpu.analysis.passes.knobs import KnobConsistencyPass
from harmony_tpu.analysis.passes.metricnames import MetricConventionsPass
from harmony_tpu.analysis.passes.spans import SpanHygienePass
from harmony_tpu.analysis.passes.spmd import SpmdDivergencePass
from harmony_tpu.analysis.passes.threads import ThreadSharedStatePass

_REGISTRY = (
    PragmaHygienePass,  # framework-owned; also always-on (see its doc)
    SpmdDivergencePass,
    ThreadSharedStatePass,
    BoundedResourcePass,
    UseAfterDonatePass,
    FaultSiteRegistryPass,
    EventKindRegistryPass,
    KnobConsistencyPass,
    SpanHygienePass,
    JitHygienePass,
    MetricConventionsPass,
)


def all_passes() -> List[Pass]:
    return [cls() for cls in _REGISTRY]


def get_pass(name: str) -> Pass:
    for cls in _REGISTRY:
        if cls.name == name:
            return cls()
    raise KeyError(
        f"unknown lint pass {name!r}; known: "
        f"{sorted(c.name for c in _REGISTRY)}")
