"""span-hygiene: tracer spans close on every path.

``trace_span`` is a context manager precisely so the exception path
stops the span and emits it (span.py: the ``finally`` stamps
``stop_sec`` and emits). A span opened positionally —
``cm = trace_span(...); cm.__enter__()`` — leaks on any raise between
enter and exit: the span never emits, the flight recorder ring never
sees it, and the trace timeline silently loses the failing subtree,
which is exactly when you need it. Sanctioned shapes:

* ``with trace_span(...):`` (directly, possibly among other items),
* ``stack.enter_context(trace_span(...))`` — ExitStack owns the exit.

Everything else — bare statement, assignment, argument, return — is
flagged.
"""
from __future__ import annotations

import ast
from typing import List, Set

from harmony_tpu.analysis.core import CodebaseIndex, Finding, Pass


def _is_trace_span_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return ((isinstance(f, ast.Name) and f.id == "trace_span")
            or (isinstance(f, ast.Attribute) and f.attr == "trace_span"))


class SpanHygienePass(Pass):
    name = "span-hygiene"
    description = ("trace_span is opened via `with` (or ExitStack."
                   "enter_context) so exception paths still emit it")

    def run(self, index: CodebaseIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in index.files:
            if sf.tree is None:
                continue
            sanctioned: Set[int] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if _is_trace_span_call(item.context_expr):
                            sanctioned.add(id(item.context_expr))
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "enter_context"):
                    for arg in node.args:
                        if _is_trace_span_call(arg):
                            sanctioned.add(id(arg))
            for node in ast.walk(sf.tree):
                if _is_trace_span_call(node) and id(node) not in sanctioned:
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        "trace_span opened outside a `with` — the span "
                        "leaks (never emits) on the exception path",
                        hint="wrap the traced region in `with trace_span"
                             "(...):` or hand it to an ExitStack",
                        col=node.col_offset))
        return out
