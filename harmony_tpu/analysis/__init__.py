"""harmonylint — codebase-aware static analysis for harmony_tpu.

Every pass in this package pins an invariant this repo learned the hard
way (docs/STATIC_ANALYSIS.md has the catalog with the historical bug
each one guards):

  * per-process env/time/random state must never steer SPMD dispatch
    order (the PR 5 chunk-count rule),
  * state shared with a thread/pool callable holds its lock
    (the ``_LEG_RETRIES`` rule),
  * a donated buffer is dead after the jitted call,
  * fault sites, env knobs, tracer spans and metric names stay
    consistent with the docs and conventions that operators read.

The framework is pure stdlib (``ast`` + text) — importing it must never
pull in jax, so the CLI's thin ``lint`` subcommand stays thin.

Public surface::

    from harmony_tpu.analysis import run_lint, all_passes
    result = run_lint()                # whole harmony_tpu/ tree
    for f in result.findings: print(f.format())
"""
from __future__ import annotations

from harmony_tpu.analysis.core import (  # noqa: F401
    CodebaseIndex,
    Finding,
    LintConfig,
    LintResult,
    Pass,
    load_baseline,
    load_config,
    render_json,
    render_text,
    run_lint,
    save_baseline,
)
from harmony_tpu.analysis.passes import all_passes, get_pass  # noqa: F401

__all__ = [
    "CodebaseIndex",
    "Finding",
    "LintConfig",
    "LintResult",
    "Pass",
    "all_passes",
    "get_pass",
    "load_baseline",
    "load_config",
    "render_json",
    "render_text",
    "run_lint",
    "save_baseline",
]
